"""SQL planning: AST → MIR with name resolution and typing.

The analogue of the reference's `mz-sql` plan pipeline (name resolution in
names.rs, HIR construction in plan/query.rs, HIR→MIR decorrelation in
plan/lowering.rs). This build plans directly to MIR; uncorrelated EXISTS/IN
become semijoins, NOT IN/NOT EXISTS threshold antijoins, and equality-
correlated scalar subqueries decorrelate into grouped joins (_decorrelate_
scalar — the Q17 pattern). General correlated decorrelation is future work.

NUMERIC is fixed-point i64 with a tracked decimal scale: literals like 0.05
plan as Literal(5)@scale2, multiplication adds scales, addition aligns them —
exact arithmetic on device, mirroring the reference's libdecnumber NUMERIC
without an f64 dependency (TPUs have no f64 ALU).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np

from ..expr import relation as mir
from ..expr.scalar import CallBinary, CallUnary, CallVariadic, Column, Literal
from ..repr.types import ColType, ColumnDesc, RelationDesc
from . import ast


class PlanError(ValueError):
    pass


@dataclass(frozen=True)
class PType:
    """Planned column type: engine ColType plus NUMERIC scale."""

    col: ColType
    scale: int = 0

    @property
    def dtype(self) -> np.dtype:
        return self.col.dtype


INT = PType(ColType.INT64)
BOOL = PType(ColType.BOOL)
STRING = PType(ColType.STRING)
FLOAT = PType(ColType.FLOAT64)
DATE = PType(ColType.TIMESTAMP)
JSONB = PType(ColType.JSONB)


@dataclass(frozen=True)
class ScopeCol:
    qualifier: Optional[str]
    name: Optional[str]
    typ: PType


@dataclass
class Scope:
    cols: list

    def resolve(self, name: str, qualifier: Optional[str]) -> int:
        matches = [
            i
            for i, c in enumerate(self.cols)
            if c.name == name and (qualifier is None or c.qualifier == qualifier)
        ]
        if not matches:
            raise PlanError(f"unknown column: {qualifier + '.' if qualifier else ''}{name}")
        if len(matches) > 1:
            raise PlanError(f"ambiguous column: {name}")
        return matches[0]

    def __add__(self, other: "Scope") -> "Scope":
        return Scope(self.cols + other.cols)


@dataclass
class RowSetFinishing:
    """Host-side ordering/limit applied to peek results (the reference's
    RowSetFinishing applied in the adapter, not the dataflow)."""

    order_by: tuple = ()  # ((col_idx, desc), ...)
    limit: Optional[int] = None
    offset: int = 0
    nulls_last: tuple = ()  # per order col; aligned with order_by


@dataclass
class PlannedQuery:
    mir: Any
    scope: Scope  # output columns with names/types
    finishing: RowSetFinishing

    @property
    def desc(self) -> RelationDesc:
        return RelationDesc(
            tuple(
                ColumnDesc(c.name or f"column{i+1}", c.typ.col, scale=c.typ.scale)
                for i, c in enumerate(self.scope.cols)
            )
        )

    @property
    def dtypes(self) -> tuple:
        return tuple(c.typ.dtype for c in self.scope.cols)


_AGG_FUNCS = {
    "sum", "count", "min", "max", "avg",
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "bool_and", "bool_or",
    "string_agg", "array_agg", "list_agg", "jsonb_agg",
}
_BASIC_AGGS = {"string_agg", "array_agg", "list_agg", "jsonb_agg"}


@dataclass(frozen=True)
class _AggRef:
    """Internal AST placeholder for an extracted aggregate call."""

    index: int


# functions that only exist as window functions (aggregates become window
# functions when called with OVER)
_WINDOW_FUNCS = {
    "row_number", "rank", "dense_rank", "ntile",
    "lag", "lead", "first_value", "last_value",
}


@dataclass(frozen=True)
class _WinRef:
    """Internal AST placeholder for an extracted window function call."""

    index: int


def _map_window_spec(spec, fn):
    """Apply `fn` to every expression inside an OVER spec (None-safe)."""
    if spec is None:
        return None
    return ast.WindowSpec(
        tuple(fn(p) for p in spec.partition_by),
        tuple(replace(o, expr=fn(o.expr)) for o in spec.order_by),
    )


def _parse_interval(text: str) -> tuple[int, int]:
    """'1 year 2 months 3 days' → (months, days). Weeks fold into days;
    sub-day fields are rejected (the engine's calendar unit is days).
    The WHOLE string must tokenize — '1.5 months' or '- 3 days' error
    instead of silently dropping characters."""
    import re as _re

    if not _re.fullmatch(r"\s*([+-]?\d+\s*[a-zA-Z]+\s*)+", text):
        raise PlanError(f"cannot parse interval {text!r}")
    months = days = 0
    matched = False
    for num, unit in _re.findall(r"([+-]?\d+)\s*([a-zA-Z]+)", text):
        n = int(num)
        u = unit.lower().rstrip("s")
        matched = True
        if u in ("year", "yr", "y"):
            months += 12 * n
        elif u in ("month", "mon"):
            months += n
        elif u in ("week", "w"):
            days += 7 * n
        elif u in ("day", "d"):
            days += n
        else:
            raise PlanError(
                f"interval unit {unit!r} unsupported (DATE granularity: "
                "year/month/week/day)"
            )
    if not matched:
        raise PlanError(f"cannot parse interval {text!r}")
    return months, days


def _argtype(t: PType):
    """Decode tag for host-side multi-arg string evaluation (expr/strings.py)."""
    if t.col == ColType.STRING:
        return "str"
    if t.col == ColType.JSONB:
        return "jsonb"
    if t.col == ColType.NUMERIC:
        return ("numeric", t.scale)
    if t.col == ColType.FLOAT64:
        return "float"
    if t.col == ColType.BOOL:
        return "bool"
    return "int"


def _literal_int(e, what: str) -> int:
    if isinstance(e, ast.NumberLit) and "." not in e.value:
        return int(e.value)
    raise PlanError(f"{what} must be an integer literal")


def _rescale(e, from_scale: int, to_scale: int):
    if from_scale == to_scale:
        return e
    if to_scale > from_scale:
        return CallBinary("mul", e, Literal(10 ** (to_scale - from_scale)))
    return CallBinary("floordiv", e, Literal(10 ** (from_scale - to_scale)))


class Planner:
    def __init__(self, catalog):
        self.catalog = catalog
        self._cte_frames: list[dict] = []  # name -> ("cte", PlannedQuery) | ("rec", gid, Scope)
        self._rec_counter = 0
        # extended-protocol parameter values for the statement being planned
        # (text-format Python values: str | None), set via set_params()
        self._params: tuple | None = None

    def set_params(self, params) -> None:
        """Bind $n parameter values (tuple of str|None) for subsequent plans."""
        self._params = tuple(params) if params is not None else None

    def _lookup_cte(self, name: str):
        for frame in reversed(self._cte_frames):
            if name in frame:
                return frame[name]
        return None

    # -- expression planning -------------------------------------------------
    def plan_scalar(self, e, scope: Scope):
        """AST expr → (ScalarExpr, PType)."""
        if isinstance(e, _AggRef):
            raise PlanError("aggregate not allowed here")
        if isinstance(e, _WinRef):
            raise PlanError("window functions are only allowed in SELECT items")
        if isinstance(e, _PostCol):
            return Column(e.index), scope.cols[e.index].typ
        if isinstance(e, _PostSum):
            # sum over an all-NULL (or empty) group is NULL, not 0
            guard = CallBinary("gt", Column(e.cnt_col), Literal(0))
            null = Literal(None, e.vt.dtype.name)
            return CallVariadic("if", (guard, Column(e.sum_col), null)), e.vt
        if isinstance(e, _PostAvg):
            num = _to_float(Column(e.sum_col), e.vt)
            # nullif guard: a group whose inputs are all NULL has non-null
            # count 0 and must yield NULL, not divide by zero
            den = CallVariadic(
                "nullif", (CallUnary("cast_float", Column(e.cnt_col)), Literal(0.0, "float32"))
            )
            return CallBinary("div", num, den), FLOAT
        if isinstance(e, _PostStat):
            # var = (sum_sq - sum^2/n) / (n - ddof); stddev = sqrt(var)
            s_ = _to_float(Column(e.sum_col), e.vt)
            sq_t = PType(ColType.NUMERIC, e.vt.scale * 2) if e.vt.col == ColType.NUMERIC else e.vt
            q = _to_float(Column(e.sq_col), sq_t)
            n = CallUnary("cast_float", Column(e.cnt_col))
            mean_sq = CallBinary("div", CallBinary("mul", s_, s_), n)
            ddof = Literal(0.0 if e.pop else 1.0, "float32")
            denom = CallBinary("sub", n, ddof)
            safe = CallVariadic("if", (CallBinary("gt", denom, Literal(0.0, "float32")), denom, Literal(1.0, "float32")))
            var = CallBinary("div", CallBinary("sub", q, mean_sq), safe)
            var = CallVariadic("if", (CallBinary("gt", denom, Literal(0.0, "float32")), var, Literal(0.0, "float32")))
            if e.sqrt:
                return CallUnary("sqrt", var), FLOAT
            return var, FLOAT
        if isinstance(e, ast.Param):
            if self._params is None or not (1 <= e.index <= len(self._params)):
                raise PlanError(f"parameter ${e.index} not bound")
            v = self._params[e.index - 1]
            # text-protocol values are typed structurally, never spliced back
            # into SQL text (the round-1 re-literalizing shim is gone).
            # Known limitation: a digits-only value bound against a TEXT
            # column types as INT (pg infers parameter types from context;
            # this planner does not yet)
            if v is None:
                return Literal(None), INT
            if not isinstance(v, str):
                # programmatic callers may bind Python values directly; the
                # wire path always delivers text-format strings
                v = str(v)
            import re as _re

            if _re.fullmatch(r"\d{4}-\d{2}-\d{2}", v):
                from ..storage.generator import date_num

                y, mo, d = (int(x) for x in v.split("-"))
                return Literal(int(date_num(y, mo, d))), DATE
            s = v.lstrip("+")
            if _re.fullmatch(r"-?\d+", s):
                return Literal(int(s)), INT
            m = _re.fullmatch(r"-?(\d*)\.(\d+)", s)
            if m:
                scale = len(m.group(2))
                neg = s.startswith("-")
                iv = int(m.group(1) or "0") * 10**scale + int(m.group(2))
                return Literal(-iv if neg else iv), PType(ColType.NUMERIC, scale)
            if v.lower() in ("t", "true", "f", "false"):
                return Literal(v.lower() in ("t", "true"), "bool"), BOOL
            return Literal(self.catalog.dict.encode(v)), STRING
        if isinstance(e, ast.Ident):
            i = scope.resolve(e.name, e.qualifier)
            return Column(i), scope.cols[i].typ
        if isinstance(e, ast.NumberLit):
            if "e" in e.value or "E" in e.value:
                # scientific notation is always a float literal (f32, the
                # device float precision — repr/types.py FLOAT64 rule)
                import numpy as _np

                return Literal(float(_np.float32(e.value)), "float32"), FLOAT
            if "." in e.value:
                intpart, frac = e.value.split(".")
                scale = len(frac)
                v = int(intpart or "0") * 10**scale + int(frac)
                return Literal(v), PType(ColType.NUMERIC, scale)
            return Literal(int(e.value)), INT
        if isinstance(e, ast.StringLit):
            return Literal(self.catalog.dict.encode(e.value)), STRING
        if isinstance(e, ast.BoolLit):
            return Literal(e.value, "bool"), BOOL
        if isinstance(e, ast.NullLit):
            # untyped NULL: int64 carrier; 3VL makes the dtype inert
            return Literal(None), INT
        if isinstance(e, ast.DateLit):
            from ..storage.generator import date_num

            y, m, d = (int(x) for x in e.value.split("-"))
            return Literal(int(date_num(y, m, d))), DATE
        if isinstance(e, ast.UnaryOp):
            v, t = self.plan_scalar(e.expr, scope)
            if e.op == "-":
                return CallUnary("neg", v), t
            if e.op == "not":
                return CallUnary("not", v), BOOL
            raise PlanError(f"unary {e.op}")
        if isinstance(e, ast.BinaryOp):
            return self._plan_binary(e, scope)
        if isinstance(e, ast.Between):
            lo = ast.BinaryOp(">=", e.expr, e.low)
            hi = ast.BinaryOp("<=", e.expr, e.high)
            both = ast.BinaryOp("and", lo, hi)
            if e.negated:
                both = ast.UnaryOp("not", both)
            return self.plan_scalar(both, scope)
        if isinstance(e, ast.InList):
            if any(isinstance(i, ast.Subquery) for i in e.items):
                raise PlanError("IN (SELECT …) must be planned at relation level")
            ors = None
            for item in e.items:
                eq = ast.BinaryOp("=", e.expr, item)
                ors = eq if ors is None else ast.BinaryOp("or", ors, eq)
            if e.negated:
                ors = ast.UnaryOp("not", ors)
            return self.plan_scalar(ors, scope)
        if isinstance(e, ast.IsNull):
            v, _t = self.plan_scalar(e.expr, scope)
            return CallUnary("is_not_null" if e.negated else "is_null", v), BOOL
        if isinstance(e, ast.Case):
            return self._plan_case(e, scope)
        if isinstance(e, ast.Cast):
            return self._plan_cast(e, scope)
        if isinstance(e, ast.FuncCall):
            return self._plan_func(e, scope)
        if isinstance(e, ast.Subquery):
            raise PlanError("scalar subqueries not supported yet")
        raise PlanError(f"unsupported expression: {e!r}")

    def _plan_binary(self, e: ast.BinaryOp, scope: Scope):
        op = e.op
        # DATE ± INTERVAL (and INTERVAL + DATE): calendar arithmetic planned
        # structurally — months via the clamping add_months kernel, days as
        # plain addition (mz-repr Interval, DATE-granularity slice)
        if op in ("+", "-") and (
            isinstance(e.right, ast.IntervalLit) or isinstance(e.left, ast.IntervalLit)
        ):
            if isinstance(e.left, ast.IntervalLit):
                if op == "-":
                    raise PlanError("cannot subtract a date from an interval")
                date_ast, iv = e.right, e.left
            else:
                date_ast, iv = e.left, e.right
            months, days = _parse_interval(iv.value)
            if op == "-":
                months, days = -months, -days
            v, vt = self.plan_scalar(date_ast, scope)
            if vt.col != ColType.TIMESTAMP:
                raise PlanError("interval arithmetic requires a date operand")
            # pg/Materialize order: months FIRST (with end-of-month clamp),
            # then days — '1995-03-31' - '1 month 1 day' is Feb 27, not the
            # day-first Feb 28
            if months:
                v = CallBinary("add_months", v, Literal(months))
            if days:
                v = CallBinary("add", v, Literal(days))
            return v, DATE
        if isinstance(e.left, ast.IntervalLit) or isinstance(e.right, ast.IntervalLit):
            raise PlanError(f"INTERVAL unsupported with operator {op}")
        if op in ("and", "or"):
            l, _ = self.plan_scalar(e.left, scope)
            r, _ = self.plan_scalar(e.right, scope)
            return CallBinary(op, l, r), BOOL
        l, lt = self.plan_scalar(e.left, scope)
        r, rt = self.plan_scalar(e.right, scope)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            if op not in ("=", "<>") and ColType.JSONB in (lt.col, rt.col):
                raise PlanError(
                    "jsonb ordering comparisons are not supported "
                    "(equality and grouping are)"
                )
            if op in ("=", "<>") and {lt.col, rt.col} == {
                ColType.JSONB, ColType.STRING
            }:
                # jsonb equality is CANONICAL-text equality: a verbatim text
                # literal with different spacing/key order must re-encode
                # canonically, or the code comparison is silently false
                def canon(expr, t):
                    if t.col != ColType.STRING:
                        return expr
                    if isinstance(expr, Literal) and expr.value is not None:
                        from ..expr.strings import json_canonical

                        try:
                            txt = json_canonical(self.catalog.dict.decode(expr.value))
                        except ValueError as exc:
                            raise PlanError(
                                f"invalid input syntax for type jsonb: {exc}"
                            ) from exc
                        return Literal(self.catalog.dict.encode(txt))
                    return self._dictfunc(("jsonb_parse",), (expr,), ("str",), "string")

                l, r = canon(l, lt), canon(r, rt)
                fn = "eq" if op == "=" else "ne"
                return CallBinary(fn, l, r), BOOL
            if (
                op not in ("=", "<>")
                and ColType.STRING in (lt.col, rt.col)
            ):
                # dictionary codes are insertion-ordered: inequality must
                # compare DECODED strings (host path; fused falls back).
                # Equality on codes stays exact and device-native.
                if isinstance(l, Literal) and l.value is None:
                    return Literal(None, "int8"), BOOL  # NULL cmp is NULL
                if isinstance(r, Literal) and r.value is None:
                    return Literal(None, "int8"), BOOL
                if lt.col != rt.col:
                    raise PlanError("cannot compare string with non-string")
                fn = {"<": "str_lt", "<=": "str_lte", ">": "str_gt", ">=": "str_gte"}[op]
                return (
                    self._dictfunc((fn,), (l, r), ("str", "str"), "bool"),
                    BOOL,
                )
            l, r, _t = self._align(l, lt, r, rt)
            fn = {"=": "eq", "<>": "ne", "<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}[op]
            return CallBinary(fn, l, r), BOOL
        if op in ("+", "-"):
            l, r, t = self._align(l, lt, r, rt)
            return CallBinary("add" if op == "+" else "sub", l, r), t
        if op == "*":
            t = self._arith_type(lt, rt)
            if t.col == ColType.NUMERIC:
                return CallBinary("mul", l, r), PType(ColType.NUMERIC, lt.scale + rt.scale)
            return CallBinary("mul", l, r), t
        if op == "/":
            t = self._arith_type(lt, rt)
            if t.col == ColType.FLOAT64:
                return CallBinary("div", l, r), FLOAT
            if t.col == ColType.NUMERIC:
                # numeric division: scale result to max(l,r) scale
                target = max(lt.scale, rt.scale)
                num = CallBinary("mul", l, Literal(10 ** (target + rt.scale - lt.scale)))
                return CallBinary("div", num, r), PType(ColType.NUMERIC, target)
            return CallBinary("div", l, r), INT
        if op == "%":
            return CallBinary("mod", l, r), INT
        if op in ("->", "->>"):
            if lt.col != ColType.JSONB:
                raise PlanError(f"{op} requires a jsonb left operand")
            as_text = op == "->>"
            out_t = STRING if as_text else JSONB
            fname = "json_get_text" if as_text else "json_get"
            if (
                isinstance(r, CallUnary)
                and r.func == "neg"
                and isinstance(r.expr, Literal)
            ):
                r = Literal(-r.expr.value, r.expr.dtype)  # j -> -1 (from end)
            if isinstance(r, Literal) and r.value is not None:
                key = (
                    self.catalog.dict.decode(r.value)
                    if rt.col == ColType.STRING
                    else int(r.value)
                )
                return (
                    self._dictfunc((fname, key), (l,), ("str",), "string"),
                    out_t,
                )
            raise PlanError(f"{op} key must be a literal string or integer")
        if op in ("like", "not_like", "ilike", "not_ilike"):
            if lt.col != ColType.STRING:
                raise PlanError("LIKE requires a string operand")
            ci = "ilike" in op
            if isinstance(r, Literal) and rt.col == ColType.STRING and r.value is not None:
                pat = self.catalog.dict.decode(r.value)
                d = self._dictfunc(("like", pat, ci), (l,), ("str",), "bool")
            elif rt.col == ColType.STRING:
                d = self._dictfunc(("like_dyn", ci), (l, r), ("str", "str"), "bool")
            else:
                raise PlanError("LIKE pattern must be a string")
            if op.startswith("not_"):
                d = CallUnary("not", d)
            return d, BOOL
        if op == "||":
            if ColType.STRING not in (lt.col, rt.col):
                raise PlanError("|| requires at least one string operand")
            if isinstance(l, Literal) and lt.col == ColType.STRING and l.value is not None:
                lit = self.catalog.dict.decode(l.value)
                if rt.col == ColType.STRING:
                    return self._dictfunc(("concat_l", lit), (r,), ("str",), "string"), STRING
            if isinstance(r, Literal) and rt.col == ColType.STRING and r.value is not None:
                lit = self.catalog.dict.decode(r.value)
                if lt.col == ColType.STRING:
                    return self._dictfunc(("concat_r", lit), (l,), ("str",), "string"), STRING
            return (
                self._dictfunc(
                    ("concat",), (l, r), (_argtype(lt), _argtype(rt)), "string"
                ),
                STRING,
            )
        raise PlanError(f"binary op {op}")

    def _dictfunc(self, spec, args, argtypes, out):
        from ..expr.scalar import DictFunc

        return DictFunc(tuple(spec), tuple(args), tuple(argtypes), out, self.catalog.str_tables)

    def _arith_type(self, lt: PType, rt: PType) -> PType:
        if ColType.FLOAT64 in (lt.col, rt.col):
            return FLOAT
        if ColType.NUMERIC in (lt.col, rt.col):
            return PType(ColType.NUMERIC, max(lt.scale, rt.scale))
        return INT

    def _common_type(self, lt: PType, rt: PType) -> PType:
        t = self._arith_type(lt, rt)
        if t.col == ColType.NUMERIC:
            return PType(ColType.NUMERIC, max(lt.scale, rt.scale))
        return t

    def _align_to(self, e, t: PType, target: PType):
        """Rescale/cast one planned expr to `target` (for n-ary alignment)."""
        if target.col == ColType.NUMERIC:
            from_scale = t.scale if t.col == ColType.NUMERIC else 0
            return _rescale(e, from_scale, target.scale)
        if target.col == ColType.FLOAT64 and t.col != ColType.FLOAT64:
            return _to_float(e, t)
        return e

    def _align(self, l, lt: PType, r, rt: PType):
        """Align numeric scales for add/sub/compare."""
        t = self._arith_type(lt, rt)
        if t.col == ColType.NUMERIC:
            target = max(lt.scale, rt.scale)
            l = _rescale(l, lt.scale, target)
            r = _rescale(r, rt.scale, target)
            return l, r, PType(ColType.NUMERIC, target)
        if t.col == ColType.FLOAT64:
            return _to_float(l, lt), _to_float(r, rt), FLOAT
        return l, r, t

    def _plan_case(self, e: ast.Case, scope: Scope):
        whens = e.whens
        if e.operand is not None:
            whens = tuple(
                (ast.BinaryOp("=", e.operand, cond), res) for cond, res in whens
            )
        else_, et = (
            self.plan_scalar(e.else_, scope) if e.else_ is not None else (Literal(0), INT)
        )
        result = else_
        rt = et
        for cond, res in reversed(whens):
            c, _ = self.plan_scalar(cond, scope)
            v, vt = self.plan_scalar(res, scope)
            v, result, rt = self._align(v, vt, result, rt)
            result = CallVariadic("if", (c, v, result))
        return result, rt

    def _plan_cast(self, e: ast.Cast, scope: Scope):
        from ..adapter.catalog import coltype_of

        v, vt = self.plan_scalar(e.expr, scope)
        target = coltype_of(e.typ)
        if target == ColType.JSONB:
            if vt.col == ColType.JSONB:
                return v, JSONB
            if vt.col == ColType.STRING:
                # text → jsonb: parse + canonicalize (invalid JSON → NULL,
                # documented divergence from pg's error)
                return (
                    self._dictfunc(("jsonb_parse",), (v,), ("str",), "string"),
                    JSONB,
                )
            raise PlanError("cast to jsonb supports text input")
        if vt.col == ColType.JSONB and target == ColType.STRING:
            return v, STRING  # canonical text IS the value
        if target == ColType.NUMERIC:
            scale = 2
            if vt.col == ColType.NUMERIC:
                return _rescale(v, vt.scale, scale), PType(ColType.NUMERIC, scale)
            return CallBinary("mul", CallUnary("cast_int64", v), Literal(10**scale)), PType(
                ColType.NUMERIC, scale
            )
        if target in (ColType.INT64, ColType.INT32):
            if vt.col == ColType.NUMERIC:
                return _rescale(v, vt.scale, 0), INT
            return CallUnary("cast_int64", v), INT
        if target == ColType.FLOAT64:
            return CallUnary("cast_float", _descale(v, vt)), FLOAT
        if target == ColType.BOOL:
            return CallUnary("is_true", v), BOOL
        raise PlanError(f"unsupported cast to {e.typ}")

    def _plan_func(self, e: ast.FuncCall, scope: Scope):
        name = e.name
        if e.over is not None:
            raise PlanError("window functions are only allowed in SELECT items")
        if name in _WINDOW_FUNCS:
            raise PlanError(f"window function {name} requires an OVER clause")
        if name in _AGG_FUNCS:
            raise PlanError(f"aggregate {name} not allowed in this context")
        if name == "abs":
            v, t = self.plan_scalar(e.args[0], scope)
            return CallUnary("abs", v), t
        if name in ("greatest", "least"):
            planned = [self.plan_scalar(a, scope) for a in e.args]
            t = planned[0][1]
            return CallVariadic(name, tuple(p for p, _ in planned)), t
        if name in ("extract_year", "extract_month", "extract_day"):
            v, _t = self.plan_scalar(e.args[0], scope)
            return CallUnary(name, v), INT
        if name == "sqrt":
            v, vt = self.plan_scalar(e.args[0], scope)
            return CallUnary("sqrt", _to_float(v, vt)), FLOAT
        if name == "coalesce":
            if not e.args:
                raise PlanError("coalesce needs at least one argument")
            planned = [self.plan_scalar(a, scope) for a in e.args]
            # common result type, then align every operand to it once
            common = planned[0][1]
            for _v, t in planned[1:]:
                common = self._common_type(common, t)
            aligned = tuple(
                self._align_to(v, t, common) for v, t in planned
            )
            return CallVariadic("coalesce", aligned), common
        if name == "nullif":
            if len(e.args) != 2:
                raise PlanError("nullif takes exactly two arguments")
            l, lt = self.plan_scalar(e.args[0], scope)
            r, rt = self.plan_scalar(e.args[1], scope)
            # aligned values compare; the aligned type is what decodes them
            l2, r2, t = self._align(l, lt, r, rt)
            return CallVariadic("nullif", (l2, r2)), t
        return self._plan_scalar_func_lib(e, scope)

    def _plan_scalar_func_lib(self, e: ast.FuncCall, scope: Scope):
        """The string/math/date scalar function library.

        Mirrors the accessible core of the reference's Unary/Binary/Variadic
        function registry (src/expr/src/scalar/func/macros.rs:153; string
        impls in func/impls/string.rs). String functions evaluate over
        dictionary codes via host-built tables (expr/strings.py)."""
        name = e.name
        args = e.args

        def plan(i):
            return self.plan_scalar(args[i], scope)

        def need(n_, *alts):
            if len(args) not in (n_, *alts):
                raise PlanError(f"{name} argument count")

        def str_arg(i):
            v, t = plan(i)
            if t.col != ColType.STRING:
                raise PlanError(f"{name} requires a string argument")
            return v

        def lit_str(i):
            a = args[i]
            if isinstance(a, ast.StringLit):
                return a.value
            v, t = plan(i)
            if isinstance(v, Literal) and t.col == ColType.STRING and v.value is not None:
                return self.catalog.dict.decode(v.value)
            raise PlanError(f"{name}: argument {i + 1} must be a string literal")

        def lit_int(i):
            v, t = plan(i)
            if isinstance(v, CallUnary) and v.func == "neg" and isinstance(v.expr, Literal):
                v = Literal(-v.expr.value, v.expr.dtype)
            if isinstance(v, Literal) and v.value is not None and t.col != ColType.STRING:
                return int(v.value)
            raise PlanError(f"{name}: argument {i + 1} must be an integer literal")

        # -- string → string / int / bool (dictionary-table) ----------------
        if name in ("upper", "lower", "initcap", "reverse", "md5"):
            need(1)
            return self._dictfunc((name,), (str_arg(0),), ("str",), "string"), STRING
        if name in ("trim", "btrim", "ltrim", "rtrim"):
            need(1, 2)
            f = "trim" if name == "btrim" else name
            spec = (f,) if len(args) == 1 else (f, lit_str(1))
            return self._dictfunc(spec, (str_arg(0),), ("str",), "string"), STRING
        if name in ("substr", "substring"):
            need(2, 3)
            ln = lit_int(2) if len(args) == 3 else None
            spec = ("substr", lit_int(1), ln)
            return self._dictfunc(spec, (str_arg(0),), ("str",), "string"), STRING
        if name in ("left", "right"):
            need(2)
            return self._dictfunc((name, lit_int(1)), (str_arg(0),), ("str",), "string"), STRING
        if name == "repeat":
            need(2)
            return self._dictfunc((name, lit_int(1)), (str_arg(0),), ("str",), "string"), STRING
        if name in ("lpad", "rpad"):
            need(2, 3)
            spec = (name, lit_int(1)) if len(args) == 2 else (name, lit_int(1), lit_str(2))
            return self._dictfunc(spec, (str_arg(0),), ("str",), "string"), STRING
        if name == "replace":
            need(3)
            return (
                self._dictfunc(
                    ("replace", lit_str(1), lit_str(2)), (str_arg(0),), ("str",), "string"
                ),
                STRING,
            )
        if name == "split_part":
            need(3)
            return (
                self._dictfunc(
                    ("split_part", lit_str(1), lit_int(2)), (str_arg(0),), ("str",), "string"
                ),
                STRING,
            )
        if name in ("length", "char_length", "character_length"):
            need(1)
            return self._dictfunc(("length",), (str_arg(0),), ("str",), "int64"), INT
        if name in ("bit_length", "octet_length", "ascii"):
            need(1)
            return self._dictfunc((name,), (str_arg(0),), ("str",), "int64"), INT
        if name in ("strpos", "position"):
            need(2)
            s = str_arg(0)
            try:
                sub = lit_str(1)
                return self._dictfunc(("strpos", sub), (s,), ("str",), "int64"), INT
            except PlanError:
                return (
                    self._dictfunc(("strpos",), (s, str_arg(1)), ("str", "str"), "int64"),
                    INT,
                )
        if name in ("starts_with", "ends_with"):
            need(2)
            s = str_arg(0)
            try:
                lit = lit_str(1)
                return self._dictfunc((name, lit), (s,), ("str",), "bool"), BOOL
            except PlanError:
                return (
                    self._dictfunc((name,), (s, str_arg(1)), ("str", "str"), "bool"),
                    BOOL,
                )
        if name in ("concat", "concat_ws"):
            if name == "concat_ws" and len(args) < 2:
                raise PlanError("concat_ws needs a separator and arguments")
            if not args:  # concat() is ''
                return Literal(self.catalog.dict.encode("")), STRING
            planned = [self.plan_scalar(a, scope) for a in args]
            # pg concat treats NULL string args as ''; coalesce them so the
            # NULL-propagating DictFunc matches (non-string NULLs still
            # propagate — documented divergence). concat_ws must NOT
            # coalesce: NULL args are skipped at eval time (no phantom
            # separators) and a NULL separator yields NULL — the eval layer
            # handles both (expr/scalar.py concat_ws null semantics).
            empty = Literal(self.catalog.dict.encode(""))
            vals, ats = [], []
            for v, t in planned:
                if t.col == ColType.STRING and name == "concat":
                    v = CallVariadic("coalesce", (v, empty))
                vals.append(v)
                ats.append(_argtype(t))
            return (
                self._dictfunc((name,), tuple(vals), tuple(ats), "string"),
                STRING,
            )

        # -- math -------------------------------------------------------------
        if name in ("floor", "ceil", "ceiling", "trunc") and len(args) == 1:
            v, t = plan(0)
            f = "ceil" if name == "ceiling" else name
            if t.col == ColType.NUMERIC and t.scale > 0:
                unit = Literal(10**t.scale)
                if f == "trunc":
                    q = CallBinary("div", v, unit)  # truncates toward zero
                else:
                    q = CallBinary("fdiv" if f == "floor" else "div", v, unit)
                    if f == "ceil":
                        # ceil = -floor(-v)
                        q = CallUnary("neg", CallBinary("fdiv", CallUnary("neg", v), unit))
                return CallBinary("mul", q, unit), t
            if t.col in (ColType.INT64, ColType.INT32) or (
                t.col == ColType.NUMERIC and t.scale == 0
            ):
                return v, t
            return CallUnary(f, _to_float(v, t)), FLOAT
        if name == "round" and len(args) in (1, 2):
            v, t = plan(0)
            if t.col == ColType.NUMERIC:
                digits = lit_int(1) if len(args) == 2 else 0
                if digits >= t.scale:
                    return v, t
                # half-away-from-zero at the target digit, keep the scale
                unit = Literal(10 ** (t.scale - digits))
                half = Literal(10 ** (t.scale - digits) // 2)
                pos = CallBinary("mul", CallBinary("div", CallBinary("add", v, half), unit), unit)
                neg = CallBinary("mul", CallBinary("div", CallBinary("sub", v, half), unit), unit)
                return (
                    CallVariadic("if", (CallBinary("gte", v, Literal(0)), pos, neg)),
                    t,
                )
            if len(args) == 2:
                digits = lit_int(1)
                m = Literal(float(np.float32(10.0**digits)), "float32")
                scaled = CallBinary("mul", _to_float(v, t), m)
                return CallBinary("div", CallUnary("round_half_away", scaled), m), FLOAT
            if t.col in (ColType.INT64, ColType.INT32):
                return v, t
            return CallUnary("round_half_away", _to_float(v, t)), FLOAT
        if name == "sign":
            need(1)
            v, t = plan(0)
            return CallUnary("sign", v), (FLOAT if t.col == ColType.FLOAT64 else INT)
        if name in ("exp", "ln", "log10", "log2", "sin", "cos", "tan", "cot",
                    "asin", "acos", "atan", "sinh", "cosh", "tanh", "cbrt",
                    "degrees", "radians"):
            need(1)
            v, t = plan(0)
            return CallUnary(name, _to_float(v, t)), FLOAT
        if name == "log":
            need(1, 2)
            if len(args) == 1:
                v, t = plan(0)
                return CallUnary("log10", _to_float(v, t)), FLOAT
            b, bt = plan(0)
            v, t = plan(1)
            return (
                CallBinary(
                    "div",
                    CallUnary("ln", _to_float(v, t)),
                    CallUnary("ln", _to_float(b, bt)),
                ),
                FLOAT,
            )
        if name in ("power", "pow"):
            need(2)
            l, lt = plan(0)
            r, rt = plan(1)
            return CallBinary("pow", _to_float(l, lt), _to_float(r, rt)), FLOAT
        if name == "atan2":
            need(2)
            l, lt = plan(0)
            r, rt = plan(1)
            return CallBinary("atan2", _to_float(l, lt), _to_float(r, rt)), FLOAT
        if name == "pi":
            need(0)
            return Literal(float(np.float32(np.pi)), "float32"), FLOAT
        if name == "mod":
            need(2)
            l, lt = plan(0)
            r, rt = plan(1)
            return CallBinary("mod", l, r), INT

        # -- date -------------------------------------------------------------
        if name in ("date_trunc", "date_part"):
            need(2)
            fld = lit_str(0).lower()
            v, t = plan(1)
            if name == "date_part":
                return self.plan_scalar(
                    ast.FuncCall(f"extract_{fld}", (args[1],)), scope
                )
            if fld not in ("year", "quarter", "month", "week", "day"):
                raise PlanError(f"date_trunc field {fld!r} unsupported for DATE")
            return CallUnary(f"date_trunc_{fld}", v), DATE
        if name in ("extract_dow", "extract_isodow", "extract_doy",
                    "extract_quarter", "extract_week", "extract_century",
                    "extract_decade", "extract_millennium"):
            need(1)
            v, _t = plan(0)
            return CallUnary(name, v), INT
        if name == "extract_epoch":
            need(1)
            v, _t = plan(0)
            return CallUnary("extract_epoch_date", v), INT

        # -- jsonb ------------------------------------------------------------
        if name == "jsonb_typeof":
            need(1)
            v, t = plan(0)
            if t.col != ColType.JSONB:
                raise PlanError("jsonb_typeof requires a jsonb argument")
            return self._dictfunc(("jsonb_typeof",), (v,), ("str",), "string"), STRING
        if name == "jsonb_array_length":
            need(1)
            v, t = plan(0)
            if t.col != ColType.JSONB:
                raise PlanError("jsonb_array_length requires a jsonb argument")
            return (
                self._dictfunc(("jsonb_array_length",), (v,), ("str",), "int64"),
                INT,
            )
        if name == "to_jsonb":
            need(1)
            v, t = plan(0)
            if t.col == ColType.JSONB:
                return v, JSONB
            if t.col == ColType.STRING:
                # a string becomes a JSON string value (quoted/escaped)
                return (
                    self._dictfunc(("jsonb_quote",), (v,), ("str",), "string"),
                    JSONB,
                )
            raise PlanError("to_jsonb supports jsonb/text arguments")
        raise PlanError(f"unsupported function: {name}")

    # -- relation planning ---------------------------------------------------
    def plan_query(self, q: ast.Query) -> PlannedQuery:
        frame: dict = {}
        rec_bindings: list = []
        if q.ctes:
            self._cte_frames.append(frame)
            if q.recursive:
                # declare every binding up front (bodies may reference any)
                from ..adapter.catalog import coltype_of

                for b in q.ctes:
                    if not b.columns:
                        raise PlanError(
                            f"WITH MUTUALLY RECURSIVE binding {b.name} needs "
                            "explicit column types (name type, …)"
                        )
                    gid = f"rec{self._rec_counter}_{b.name}"
                    self._rec_counter += 1
                    cols = [
                        ScopeCol(b.name, cname, PType(coltype_of(ctyp),
                                 2 if coltype_of(ctyp) == ColType.NUMERIC else 0))
                        for cname, ctyp in b.columns
                    ]
                    frame[b.name] = ("rec", gid, Scope(cols))
                for b in q.ctes:
                    pq = self.plan_query(b.query)
                    if len(pq.scope.cols) != len(b.columns):
                        raise PlanError(
                            f"binding {b.name}: body arity {len(pq.scope.cols)} "
                            f"!= declared {len(b.columns)}"
                        )
                    _k, gid, scope = frame[b.name]
                    brel = pq.mir
                    if pq.finishing.limit is not None:
                        brel = _apply_finishing_as_topk(pq)
                    rec_bindings.append(
                        (gid, tuple(c.typ.dtype for c in scope.cols), brel)
                    )
            else:
                for b in q.ctes:
                    frame[b.name] = ("cte", self.plan_query(b.query))
        try:
            rel, scope = self.plan_set_expr(q.body)
        finally:
            if q.ctes:
                self._cte_frames.pop()
        if rec_bindings:
            rel = mir.MirLetRec(tuple(rec_bindings), rel)
        order, limit, offset = q.order_by, q.limit, q.offset
        order_idx = []
        nulls_last = []
        for ob in order:
            idx = self._resolve_output_col(ob.expr, q.body, scope)
            order_idx.append((idx, ob.desc))
            nl = ob.nulls_last
            nulls_last.append(not ob.desc if nl is None else nl)
        finishing = RowSetFinishing(
            tuple(order_idx), limit, offset, tuple(nulls_last)
        )
        return PlannedQuery(rel, scope, finishing)

    def _resolve_output_col(self, e, body, scope: Scope) -> int:
        if isinstance(e, ast.NumberLit) and "." not in e.value:
            n = int(e.value)
            if not (1 <= n <= len(scope.cols)):
                raise PlanError(f"ORDER BY position {n} out of range")
            return n - 1
        if isinstance(e, ast.Ident) and e.qualifier is None:
            for i, c in enumerate(scope.cols):
                if c.name == e.name:
                    return i
        raise PlanError(f"cannot resolve ORDER BY expression {e!r}")

    def plan_set_expr(self, body):
        if isinstance(body, ast.Select):
            return self.plan_select(body)
        if isinstance(body, ast.Values):
            return self.plan_values(body)
        if isinstance(body, ast.SetOp):
            lrel, lscope = self.plan_set_expr(body.left)
            rrel, rscope = self.plan_set_expr(body.right)
            if len(lscope.cols) != len(rscope.cols):
                raise PlanError("set operands have different arities")
            op = body.op
            if op == "union_all":
                return mir.MirUnion((lrel, rrel)), lscope
            if op == "union":
                return mir.MirDistinct(mir.MirUnion((lrel, rrel))), lscope
            if op in ("except", "except_all"):
                if op == "except":
                    lrel, rrel = mir.MirDistinct(lrel), mir.MirDistinct(rrel)
                return (
                    mir.MirThreshold(mir.MirUnion((lrel, mir.MirNegate(rrel)))),
                    lscope,
                )
            if op in ("intersect", "intersect_all"):
                if op == "intersect":
                    lrel, rrel = mir.MirDistinct(lrel), mir.MirDistinct(rrel)
                # min(a,b) = a - (a - b)^+
                diff = mir.MirThreshold(mir.MirUnion((lrel, mir.MirNegate(rrel))))
                return (
                    mir.MirThreshold(mir.MirUnion((lrel, mir.MirNegate(diff)))),
                    lscope,
                )
            raise PlanError(f"set op {op}")
        if isinstance(body, ast.Query):
            pq = self.plan_query(body)
            if pq.finishing.limit is not None or pq.finishing.order_by:
                rel = _apply_finishing_as_topk(pq)
            else:
                rel = pq.mir
            return rel, pq.scope
        raise PlanError(f"unsupported query body {type(body).__name__}")

    def plan_values(self, v: ast.Values):
        if not v.rows:
            raise PlanError("VALUES needs at least one row")
        arity = len(v.rows[0])
        planned_rows = []
        types: list = [None] * arity
        for row in v.rows:
            if len(row) != arity:
                raise PlanError("VALUES rows must have equal arity")
            vals = []
            for i, e in enumerate(row):
                p, t = self.plan_scalar(e, Scope([]))
                if not isinstance(p, Literal):
                    raise PlanError("VALUES entries must be literals")
                if types[i] is None:
                    types[i] = t
                elif types[i].col != t.col:
                    # align int/numeric mixes by rescaling to the wider scale
                    if {types[i].col, t.col} == {ColType.INT64, ColType.NUMERIC}:
                        types[i] = t if t.col == ColType.NUMERIC else types[i]
                    else:
                        raise PlanError("VALUES column types must match")
                vals.append((p.value, t))
            planned_rows.append(vals)
        rows = []
        for vals in planned_rows:
            data = []
            for i, (raw, t) in enumerate(vals):
                target = types[i]
                if target.col == ColType.NUMERIC and t.scale != target.scale:
                    raw = raw * 10 ** (target.scale - t.scale)
                data.append(raw)
            rows.append((tuple(data), 1))
        rel = mir.MirConstant(
            rows=tuple(rows), dtypes=tuple(t.dtype for t in types)
        )
        scope = Scope(
            [ScopeCol(None, f"column{i+1}", t) for i, t in enumerate(types)]
        )
        return rel, scope

    def plan_select(self, sel: ast.Select):
        # 1. FROM: flatten factors + inner joins into one MirJoin
        factors: list = []
        scopes: list[Scope] = []
        on_preds: list = []
        outer_fm = getattr(self, "_pending_fm", None)
        self._pending_fm = []
        if not sel.from_:
            factors.append(mir.MirConstant(rows=(((), 1),), dtypes=()))
            scopes.append(Scope([]))
        for f in sel.from_:
            self._flatten_from(f, factors, scopes, on_preds)
        pending_fm = self._pending_fm
        self._pending_fm = outer_fm
        if pending_fm:
            # their scope slots must be the trailing ones: the FlatMap output
            # column is appended after all factor columns
            want = list(range(len(scopes) - len(pending_fm), len(scopes)))
            if [i for _n, _a, _al, i in pending_fm] != want:
                raise PlanError(
                    "correlated generate_series must come after all plain "
                    "FROM items"
                )
        # 1b. lift uncorrelated subqueries (IN / EXISTS / scalar) into join
        # factors — the decorrelation-lite path (reference: HIR→MIR lowering
        # in src/sql/src/plan/lowering.rs; correlated forms are future work)
        n_factors_pre_lift = len(factors)
        lifter = _SubqueryLifter(self, factors, scopes)
        # WHERE/ON conjuncts may register antijoins (top level only); other
        # contexts reject NOT IN/NOT EXISTS instead of silently misplanning
        new_where = None
        if sel.where is not None:
            parts = [lifter.rewrite_conjunct(c) for c in _split_and(sel.where)]
            for part in parts:
                new_where = part if new_where is None else ast.BinaryOp("and", new_where, part)
        on_preds[:] = [
            _join_and([lifter.rewrite_conjunct(c) for c in _split_and(p_)])
            for p_ in on_preds
        ]
        sel = replace(
            sel,
            where=new_where,
            items=tuple(
                ast.SelectItem(lifter.rewrite(it.expr), it.alias) for it in sel.items
            ),
            having=lifter.rewrite(sel.having) if sel.having is not None else None,
        )

        full_scope = Scope([c for s in scopes for c in s.cols])
        offsets = []
        off = 0
        for s in scopes:
            offsets.append(off)
            off += len(s.cols)

        # 2. conjuncts from ON + WHERE; split equijoin equivalences vs filters
        conjuncts = []
        for p in on_preds:
            conjuncts.extend(_split_and(p))
        if sel.where is not None:
            conjuncts.extend(_split_and(sel.where))
        conjuncts.extend(lifter.extra_conjuncts)
        temporal = [c for c in conjuncts if _contains_mz_now(c)]
        conjuncts = [c for c in conjuncts if not _contains_mz_now(c)]
        if not factors:
            # every FROM item was a correlated table function: fan out of the
            # unit relation
            factors.append(mir.MirConstant(rows=(((), 1),), dtypes=()))
        if pending_fm and len(factors) > n_factors_pre_lift:
            # a lifted subquery factor would sit AFTER the FlatMap's scope
            # slot, misaligning every post-join column index
            raise PlanError(
                "correlated generate_series cannot be combined with "
                "IN/EXISTS/scalar subqueries yet"
            )
        flat_start = len(full_scope.cols) - len(pending_fm)
        equivs: list[set] = []
        residual = []
        for c in conjuncts:
            pair = self._as_column_equality(c, full_scope, scopes, offsets)
            # equalities touching a FlatMap output column can't join factors
            # (the column doesn't exist until after the join) — filter instead
            if pair is not None and all(i < flat_start for i in pair):
                merged = False
                for cls in equivs:
                    if pair[0] in cls or pair[1] in cls:
                        cls.update(pair)
                        merged = True
                        break
                if not merged:
                    equivs.append(set(pair))
            else:
                residual.append(c)
        if len(factors) == 1:
            rel = factors[0]
        else:
            rel = mir.MirJoin(
                inputs=tuple(factors),
                equivalences=tuple(tuple(sorted(c)) for c in equivs),
            )
        scope = full_scope
        # correlated table functions fan out on top of the joined factors
        for k, (fname, fargs, _alias, _si) in enumerate(pending_fm):
            prefix = Scope(list(full_scope.cols[: flat_start + k]))
            planned_args = [self.plan_scalar(a, prefix)[0] for a in fargs]
            if len(planned_args) == 2:
                planned_args.append(Literal(1))
            rel = mir.MirFlatMap(rel, fname, tuple(planned_args))
        for c in residual:
            p, _t = self.plan_scalar(c, scope)
            rel = mir.MirFilter(rel, (p,))
        if temporal:
            rel = self._plan_temporal(rel, temporal, scope)

        # NOT IN / NOT EXISTS antijoins: rel − (rel ⋉ sub), thresholded
        for key_ast, sub_pq, is_exists in lifter.antijoins:
            n = len(scope.cols)

            def anti(rel_in, key_expr, sub_rel):
                rel_k = mir.MirMap(rel_in, (key_expr,))
                matched = mir.MirProject(
                    mir.MirJoin(
                        inputs=(rel_k, sub_rel),
                        equivalences=((n, n + 1),),
                    ),
                    tuple(range(n)),
                )
                return mir.MirThreshold(
                    mir.MirUnion((rel_in, mir.MirNegate(matched)))
                )

            if is_exists:
                sub_rel = mir.MirDistinct(
                    mir.MirProject(
                        mir.MirMap(sub_pq.mir, (Literal(1),)),
                        (len(sub_pq.scope.cols),),
                    )
                )
                rel = anti(rel, Literal(1), sub_rel)
                continue
            # NOT IN, three-valued (pg semantics): a NULL key row passes only
            # when the subquery is EMPTY; if the subquery produces any NULL,
            # no row passes (x NOT IN S is then NULL or FALSE for every x)
            key_expr, _t = self.plan_scalar(key_ast, scope)
            sub = sub_pq.mir  # arity 1
            res0 = anti(
                mir.MirFilter(rel, (CallUnary("is_not_null", key_expr),)),
                key_expr,
                mir.MirDistinct(sub),
            )
            s_nonempty = mir.MirDistinct(
                mir.MirProject(mir.MirMap(sub, (Literal(1),)), (1,))
            )
            keep_null = anti(
                mir.MirFilter(rel, (CallUnary("is_null", key_expr),)),
                Literal(1),
                s_nonempty,
            )
            s_null = mir.MirDistinct(
                mir.MirProject(
                    mir.MirMap(
                        mir.MirFilter(sub, (CallUnary("is_null", Column(0)),)),
                        (Literal(1),),
                    ),
                    (1,),
                )
            )
            rel = anti(mir.MirUnion((res0, keep_null)), Literal(1), s_null)

        # 3. aggregates?
        has_group = bool(sel.group_by)
        aggs: list[ast.FuncCall] = []
        items = [
            ast.SelectItem(self._extract_aggs(it.expr, aggs), it.alias)
            for it in sel.items
        ]
        having = self._extract_aggs(sel.having, aggs) if sel.having is not None else None
        if has_group or aggs:
            rel, scope, items, having = self._plan_reduce(
                rel, scope, sel, items, aggs, having
            )
        if having is not None:
            p, _ = self.plan_scalar(having, scope)
            rel = mir.MirFilter(rel, (p,))

        # 3.5 window functions (evaluated after grouping/HAVING, pg order)
        wins: list[ast.FuncCall] = []
        items = [
            ast.SelectItem(self._extract_windows(it.expr, wins), it.alias)
            for it in items
        ]
        if wins:
            rel, scope = self._plan_windows(rel, scope, wins)
            items = [
                ast.SelectItem(self._rewrite_wins(it.expr), it.alias)
                for it in items
            ]

        # 4. projection (names come from the pre-rewrite select items)
        out_exprs = []
        out_cols = []
        for it, orig in zip(items, sel.items):
            if isinstance(it.expr, ast.Star):
                for i, c in enumerate(scope.cols):
                    if it.expr.qualifier is None or c.qualifier == it.expr.qualifier:
                        out_exprs.append((Column(i), c.typ))
                        out_cols.append(ScopeCol(c.qualifier, c.name, c.typ))
            else:
                p, t = self.plan_scalar(it.expr, scope)
                out_exprs.append((p, t))
                name = orig.alias or _default_name(orig.expr)
                out_cols.append(ScopeCol(None, name, t))
        arity_in = len(scope.cols)
        rel = mir.MirMap(rel, tuple(p for p, _ in out_exprs))
        rel = mir.MirProject(rel, tuple(range(arity_in, arity_in + len(out_exprs))))
        out_scope = Scope(out_cols)
        if sel.distinct:
            rel = mir.MirDistinct(rel)
        return rel, out_scope

    def _plan_temporal(self, rel, temporal, scope: Scope):
        """mz_now() comparisons → validity windows (MirTemporalFilter).

        mz_now() <= e  →  valid until e+1     mz_now() >= e  →  valid from e
        mz_now() <  e  →  valid until e       mz_now() >  e  →  valid from e+1
        (mirrored when mz_now() is on the right side).
        """
        lowers, uppers = [], []
        for c in temporal:
            if isinstance(c, ast.Between) and _is_mz_now(c.expr) and not c.negated:
                lo, _ = self.plan_scalar(c.low, scope)
                hi, _ = self.plan_scalar(c.high, scope)
                lowers.append(lo)
                uppers.append(CallBinary("add", hi, Literal(1)))
                continue
            if not isinstance(c, ast.BinaryOp):
                raise PlanError("mz_now() only supported in comparison predicates")
            lhs_now = _is_mz_now(c.left)
            rhs_now = _is_mz_now(c.right)
            if lhs_now == rhs_now:
                raise PlanError("mz_now() must appear alone on one side of a comparison")
            other = c.right if lhs_now else c.left
            if _contains_mz_now(other):
                raise PlanError("mz_now() must appear alone on one side of a comparison")
            e, _t = self.plan_scalar(other, scope)
            op = c.op
            if rhs_now:  # e OP mz_now() → mz_now() flip(OP) e
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}[op]
            plus1 = CallBinary("add", e, Literal(1))
            if op == "<=":
                uppers.append(plus1)
            elif op == "<":
                uppers.append(e)
            elif op == ">=":
                lowers.append(e)
            elif op == ">":
                lowers.append(plus1)
            elif op == "=":
                lowers.append(e)
                uppers.append(plus1)
            else:
                raise PlanError(f"mz_now() unsupported with operator {op}")
        return mir.MirTemporalFilter(rel, tuple(lowers), tuple(uppers))

    def _flatten_from(self, f, factors, scopes, on_preds):
        if isinstance(f, ast.TableRef):
            cte = self._lookup_cte(f.name)
            if cte is not None:
                alias = f.alias or f.name
                if cte[0] == "rec":
                    _k, gid, rscope = cte
                    factors.append(mir.MirGet(gid, len(rscope.cols)))
                    scopes.append(
                        Scope([ScopeCol(alias, c.name, c.typ) for c in rscope.cols])
                    )
                    return
                pq = cte[1]
                rel = pq.mir
                if pq.finishing.limit is not None:
                    rel = _apply_finishing_as_topk(pq)
                factors.append(rel)
                scopes.append(
                    Scope([ScopeCol(alias, c.name, c.typ) for c in pq.scope.cols])
                )
                return
            item = self.catalog.get(f.name)
            if item.desc is None:
                raise PlanError(f"{f.name} has no relation description")
            alias = f.alias or f.name
            if item.kind == "view":
                # inline the stored view MIR (the reference inlines view
                # definitions during name resolution too)
                pq = item.mir
                rel = pq.mir
                if pq.finishing.limit is not None:
                    rel = _apply_finishing_as_topk(pq)
                factors.append(rel)
                scopes.append(
                    Scope([ScopeCol(alias, c.name, c.typ) for c in pq.scope.cols])
                )
                return
            factors.append(mir.MirGet(item.global_id, item.desc.arity))
            scopes.append(
                Scope(
                    [
                        ScopeCol(alias, c.name, PType(c.typ, c.scale if c.typ == ColType.NUMERIC else 0))
                        for c in item.desc.columns
                    ]
                )
            )
            return
        if isinstance(f, ast.TableFuncRef):
            if f.name == "generate_series":
                if len(f.args) not in (2, 3):
                    raise PlanError("generate_series takes 2 or 3 arguments")
                alias = f.alias or "generate_series"
                try:
                    vals = []
                    for a in f.args:
                        p, _t = self.plan_scalar(a, Scope([]))
                        if (
                            isinstance(p, CallUnary)
                            and p.func == "neg"
                            and isinstance(p.expr, Literal)
                        ):
                            p = Literal(-p.expr.value, p.expr.dtype)
                        if not isinstance(p, Literal):
                            raise PlanError("non-literal")
                        vals.append(int(p.value))
                except PlanError:
                    # CORRELATED series (args reference other FROM columns):
                    # becomes a FlatMap applied on top of the joined factors
                    # (reference MirRelationExpr::FlatMap, rendered at
                    # compute/src/render/flat_map.rs). Must trail the plain
                    # factors so its output column is the last one.
                    if getattr(self, "_no_flatmaps", False):
                        raise PlanError(
                            "correlated generate_series is only supported as "
                            "a top-level FROM item"
                        )
                    self._pending_fm.append(
                        (f.name, tuple(f.args), alias, len(scopes))
                    )
                    scopes.append(Scope([ScopeCol(alias, alias, INT)]))
                    return
                lo, hi = vals[0], vals[1]
                step = vals[2] if len(vals) == 3 else 1
                if step == 0:
                    raise PlanError("generate_series step must be nonzero")
                rows = tuple(((v,), 1) for v in range(lo, hi + (1 if step > 0 else -1), step))
                factors.append(
                    mir.MirConstant(rows=rows, dtypes=(np.dtype(np.int64),))
                )
                scopes.append(Scope([ScopeCol(alias, alias, INT)]))
                return
            raise PlanError(f"unsupported table function {f.name}")
        if isinstance(f, ast.SubqueryRef):
            pq = self.plan_query(f.query)
            rel = pq.mir
            if pq.finishing.limit is not None:
                rel = _apply_finishing_as_topk(pq)
            factors.append(rel)
            scopes.append(
                Scope([ScopeCol(f.alias, c.name, c.typ) for c in pq.scope.cols])
            )
            return
        if isinstance(f, ast.JoinClause):
            if f.kind == "cross":
                self._flatten_from(f.left, factors, scopes, on_preds)
                self._flatten_from(f.right, factors, scopes, on_preds)
                return
            if f.kind != "inner":
                rel, scope = self._plan_outer_join(f)
                factors.append(rel)
                scopes.append(scope)
                return
            self._flatten_from(f.left, factors, scopes, on_preds)
            self._flatten_from(f.right, factors, scopes, on_preds)
            if f.on is not None:
                on_preds.append(f.on)
            return
        raise PlanError(f"unsupported FROM clause {type(f).__name__}")

    def _plan_factor_rel(self, f):
        """Plan one table factor (incl. nested joins) to a (rel, scope).

        Correlated table functions are not supported inside nested factor
        trees (outer joins etc.) — `_no_flatmaps` makes them error cleanly.
        """
        prev_guard = getattr(self, "_no_flatmaps", False)
        self._no_flatmaps = True
        try:
            return self._plan_factor_rel_inner(f)
        finally:
            self._no_flatmaps = prev_guard

    def _plan_factor_rel_inner(self, f):
        factors: list = []
        scopes: list[Scope] = []
        on_preds: list = []
        self._flatten_from(f, factors, scopes, on_preds)
        scope = Scope([c for s in scopes for c in s.cols])
        if len(factors) == 1:
            rel = factors[0]
        else:
            offsets = []
            off = 0
            for s in scopes:
                offsets.append(off)
                off += len(s.cols)
            equivs, residual = self._split_equalities(on_preds, scope, scopes, offsets)
            rel = mir.MirJoin(
                inputs=tuple(factors),
                equivalences=tuple(tuple(sorted(c)) for c in equivs),
            )
            for c in residual:
                p, _t = self.plan_scalar(c, scope)
                rel = mir.MirFilter(rel, (p,))
            on_preds = []
        for c in on_preds:
            p, _t = self.plan_scalar(c, scope)
            rel = mir.MirFilter(rel, (p,))
        return rel, scope

    def _split_equalities(self, preds, full_scope, scopes, offsets):
        """Partition conjuncts into join equivalence classes and residuals."""
        conjuncts = []
        for p in preds:
            conjuncts.extend(_split_and(p))
        equivs: list[set] = []
        residual = []
        for c in conjuncts:
            pair = self._as_column_equality(c, full_scope, scopes, offsets)
            if pair is not None:
                merged = False
                for cls in equivs:
                    if pair[0] in cls or pair[1] in cls:
                        cls.update(pair)
                        merged = True
                        break
                if not merged:
                    equivs.append(set(pair))
            else:
                residual.append(c)
        return equivs, residual

    def _plan_outer_join(self, f: ast.JoinClause):
        """LEFT/RIGHT/FULL OUTER JOIN via the union/compensation lowering
        (reference: HIR→MIR outer-join lowering, plan/lowering.rs:1581):

            inner ∪ (unmatched preserved rows × NULL row for the other side)

        where unmatched = preserved − (preserved ⋉ distinct matched rows),
        the semijoin taken with null-safe (IS NOT DISTINCT FROM) equality so
        preserved rows containing NULLs still count as matched.
        """
        lrel, lscope = self._plan_factor_rel(f.left)
        rrel, rscope = self._plan_factor_rel(f.right)
        n_l, n_r = len(lscope.cols), len(rscope.cols)
        full_scope = Scope(list(lscope.cols) + list(rscope.cols))
        if f.on is None:
            raise PlanError("outer joins require an ON clause")
        equivs, residual = self._split_equalities(
            [f.on], full_scope, [lscope, rscope], [0, n_l]
        )
        inner = mir.MirJoin(
            inputs=(lrel, rrel),
            equivalences=tuple(tuple(sorted(c)) for c in equivs),
        )
        for c in residual:
            p, _t = self.plan_scalar(c, full_scope)
            inner = mir.MirFilter(inner, (p,))

        def nulls_for(scope_cols):
            return tuple(
                Literal(None, t.col.dtype.name)
                for t in (c.typ for c in scope_cols)
            )

        def compensation(side_rel, side_cols_range, other_scope_cols, reorder):
            matched = mir.MirDistinct(mir.MirProject(inner, tuple(side_cols_range)))
            n = len(side_cols_range)
            semi = mir.MirJoin(
                inputs=(side_rel, matched),
                equivalences=tuple((i, n + i) for i in range(n)),
                null_safe=True,
            )
            semi_kept = mir.MirProject(semi, tuple(range(n)))
            unmatched = mir.MirUnion((side_rel, mir.MirNegate(semi_kept)))
            padded = mir.MirMap(unmatched, nulls_for(other_scope_cols))
            if reorder is not None:
                padded = mir.MirProject(padded, reorder)
            return padded

        parts = [inner]
        if f.kind in ("left", "full"):
            parts.append(
                compensation(lrel, range(n_l), rscope.cols, None)
            )
        if f.kind in ("right", "full"):
            # Map appends NULL left-cols after the right row; reorder to
            # (left NULLs, right cols)
            reorder = tuple(range(n_r, n_r + n_l)) + tuple(range(n_r))
            parts.append(
                compensation(rrel, range(n_l, n_l + n_r), lscope.cols, reorder)
            )
        rel = mir.MirUnion(tuple(parts)) if len(parts) > 1 else parts[0]
        return rel, full_scope

    def _as_column_equality(self, c, full_scope, scopes, offsets):
        """col = col crossing two inputs → (global_col_a, global_col_b)."""
        if not (isinstance(c, ast.BinaryOp) and c.op == "="):
            return None
        l, r = c.left, c.right
        if not (isinstance(l, ast.Ident) and isinstance(r, ast.Ident)):
            return None
        try:
            li = full_scope.resolve(l.name, l.qualifier)
            ri = full_scope.resolve(r.name, r.qualifier)
        except PlanError:
            return None
        # find owning inputs
        def owner(i):
            for k in range(len(offsets) - 1, -1, -1):
                if i >= offsets[k]:
                    return k
            return 0

        if owner(li) == owner(ri):
            return None
        return (li, ri)

    def _extract_aggs(self, e, aggs: list):
        """Replace aggregate FuncCalls with _AggRef placeholders."""
        if e is None or isinstance(e, (ast.NumberLit, ast.StringLit, ast.BoolLit, ast.NullLit, ast.DateLit, ast.Ident, ast.Star)):
            return e
        if isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS and e.over is None:
            for i, a in enumerate(aggs):
                if a == e:
                    return _AggRef(i)
            aggs.append(e)
            return _AggRef(len(aggs) - 1)
        if isinstance(e, ast.UnaryOp):
            return replace(e, expr=self._extract_aggs(e.expr, aggs))
        if isinstance(e, ast.BinaryOp):
            return replace(
                e,
                left=self._extract_aggs(e.left, aggs),
                right=self._extract_aggs(e.right, aggs),
            )
        if isinstance(e, ast.FuncCall):
            # window calls: aggregates may appear in args AND in the OVER
            # spec's partition/order expressions of a grouped query
            return replace(
                e,
                args=tuple(self._extract_aggs(a, aggs) for a in e.args),
                over=_map_window_spec(e.over, lambda a: self._extract_aggs(a, aggs)),
            )
        if isinstance(e, ast.Cast):
            return replace(e, expr=self._extract_aggs(e.expr, aggs))
        if isinstance(e, ast.Case):
            return ast.Case(
                self._extract_aggs(e.operand, aggs) if e.operand else None,
                tuple(
                    (self._extract_aggs(c, aggs), self._extract_aggs(r, aggs))
                    for c, r in e.whens
                ),
                self._extract_aggs(e.else_, aggs) if e.else_ else None,
            )
        if isinstance(e, ast.Between):
            return replace(
                e,
                expr=self._extract_aggs(e.expr, aggs),
                low=self._extract_aggs(e.low, aggs),
                high=self._extract_aggs(e.high, aggs),
            )
        if isinstance(e, ast.InList):
            return replace(
                e,
                expr=self._extract_aggs(e.expr, aggs),
                items=tuple(self._extract_aggs(i, aggs) for i in e.items),
            )
        if isinstance(e, ast.IsNull):
            return replace(e, expr=self._extract_aggs(e.expr, aggs))
        return e

    def _extract_windows(self, e, wins: list):
        """Replace window FuncCalls (over != None) with _WinRef placeholders."""
        if e is None or isinstance(
            e,
            (
                ast.NumberLit, ast.StringLit, ast.BoolLit, ast.NullLit,
                ast.DateLit, ast.Ident, ast.Star,
                _PostCol, _PostAvg, _PostSum, _PostStat,
            ),
        ):
            return e
        if isinstance(e, ast.FuncCall) and e.over is not None:
            for i, w in enumerate(wins):
                if w == e:
                    return _WinRef(i)
            wins.append(e)
            return _WinRef(len(wins) - 1)
        if isinstance(e, ast.UnaryOp):
            return replace(e, expr=self._extract_windows(e.expr, wins))
        if isinstance(e, ast.BinaryOp):
            return replace(
                e,
                left=self._extract_windows(e.left, wins),
                right=self._extract_windows(e.right, wins),
            )
        if isinstance(e, ast.FuncCall):
            return replace(
                e, args=tuple(self._extract_windows(a, wins) for a in e.args)
            )
        if isinstance(e, ast.Cast):
            return replace(e, expr=self._extract_windows(e.expr, wins))
        if isinstance(e, ast.Case):
            return ast.Case(
                self._extract_windows(e.operand, wins) if e.operand else None,
                tuple(
                    (self._extract_windows(c, wins), self._extract_windows(r, wins))
                    for c, r in e.whens
                ),
                self._extract_windows(e.else_, wins) if e.else_ else None,
            )
        if isinstance(e, ast.Between):
            return replace(
                e,
                expr=self._extract_windows(e.expr, wins),
                low=self._extract_windows(e.low, wins),
                high=self._extract_windows(e.high, wins),
            )
        if isinstance(e, ast.InList):
            return replace(
                e,
                expr=self._extract_windows(e.expr, wins),
                items=tuple(self._extract_windows(i, wins) for i in e.items),
            )
        if isinstance(e, ast.IsNull):
            return replace(e, expr=self._extract_windows(e.expr, wins))
        return e

    def _plan_windows(self, rel, scope, wins: list):
        """Plan extracted window calls: per distinct OVER spec, map the
        partition/order/argument expressions onto the relation and add one
        MirWindow; finally project away the helper columns, keeping the
        original scope plus one output column per call.

        The reference plans window functions into whole-group-recompute
        reduces during HIR lowering (src/sql/src/plan/query.rs window
        planning, src/sql/src/plan/lowering.rs:1581); the net SQL surface
        here is the same, the physical plan is the batched Window operator.
        """
        n0 = len(scope.cols)
        groups: list[tuple] = []  # (WindowSpec, [win index, ...])
        for i, w in enumerate(wins):
            for spec, idxs in groups:
                if spec == w.over:
                    idxs.append(i)
                    break
            else:
                groups.append((w.over, [i]))

        cur = n0
        func_abs: list[int] = []  # absolute column position per emitted func
        func_types: list = []
        self._win_repl = {}
        pending: list[tuple] = []  # (win_i, kind, payload into func index space)

        for spec, idxs in groups:
            map_exprs: list = []
            if spec.partition_by:
                for p in spec.partition_by:
                    pe, _pt = self.plan_scalar(p, scope)
                    map_exprs.append(pe)
            else:
                map_exprs.append(Literal(1))
            npart = len(map_exprs)
            part_cols = tuple(range(cur, cur + npart))
            for o in spec.order_by:
                oe, ot = self.plan_scalar(o.expr, scope)
                if ot.col in (ColType.STRING, ColType.JSONB):
                    # the window kernel ranks on device by dictionary code
                    # (insertion order) — reject rather than mis-order
                    raise PlanError(
                        "window ORDER BY on a string column is not supported "
                        "(device ordering is by dictionary code)"
                    )
                map_exprs.append(oe)
            ord_cols = tuple(range(cur + npart, cur + npart + len(spec.order_by)))
            order_by = tuple(
                (c, o.desc) for c, o in zip(ord_cols, spec.order_by)
            )
            nulls_last = (
                tuple(
                    (not o.desc) if o.nulls_last is None else o.nulls_last
                    for o in spec.order_by
                )
                or None
            )

            funcs: list = []
            k0 = len(func_abs)
            for wi in idxs:
                call = wins[wi]
                name = call.name
                if call.distinct:
                    raise PlanError("DISTINCT is not supported in window functions")

                def arg_col(a):
                    v, vt = self.plan_scalar(a, scope)
                    map_exprs.append(v)
                    return cur + len(map_exprs) - 1, vt

                if name in ("row_number", "rank", "dense_rank"):
                    funcs.append(mir.MirWindowFunc(name))
                    pending.append((wi, "col", (k0 + len(funcs) - 1, INT)))
                elif name == "ntile":
                    nt = _literal_int(call.args[0], "ntile bucket count")
                    funcs.append(mir.MirWindowFunc("ntile", None, nt))
                    pending.append((wi, "col", (k0 + len(funcs) - 1, INT)))
                elif name == "count" and (call.is_star or not call.args):
                    funcs.append(mir.MirWindowFunc("count"))
                    pending.append((wi, "col", (k0 + len(funcs) - 1, INT)))
                elif name == "avg":
                    acol, vt = arg_col(call.args[0])
                    funcs.append(mir.MirWindowFunc("sum", acol))
                    s_k = k0 + len(funcs) - 1
                    funcs.append(mir.MirWindowFunc("count", acol))
                    c_k = k0 + len(funcs) - 1
                    pending.append((wi, "avg", (s_k, c_k, vt)))
                elif name in ("lag", "lead"):
                    if len(call.args) >= 3:
                        raise PlanError(f"{name} default argument not supported")
                    acol, vt = arg_col(call.args[0])
                    off = (
                        _literal_int(call.args[1], f"{name} offset")
                        if len(call.args) >= 2
                        else 1
                    )
                    funcs.append(mir.MirWindowFunc(name, acol, off))
                    pending.append((wi, "col", (k0 + len(funcs) - 1, vt)))
                elif name in ("first_value", "last_value", "sum", "min", "max", "count"):
                    acol, vt = arg_col(call.args[0])
                    if name in ("min", "max") and vt.col in (
                        ColType.STRING, ColType.JSONB
                    ):
                        raise PlanError(
                            f"window {name} over a string/jsonb column is not "
                            "supported (device ordering is by dictionary code)"
                        )
                    out_t = INT if name == "count" else vt
                    funcs.append(mir.MirWindowFunc(name, acol))
                    pending.append((wi, "col", (k0 + len(funcs) - 1, out_t)))
                else:
                    raise PlanError(f"window function {name} not supported")

            rel = mir.MirMap(rel, tuple(map_exprs))
            base = cur + len(map_exprs)
            rel = mir.MirWindow(
                rel, part_cols, order_by, tuple(funcs), nulls_last
            )
            for fi in range(len(funcs)):
                func_abs.append(base + fi)
            cur = base + len(funcs)

        # project: original columns ++ every window output, in emission order
        rel = mir.MirProject(rel, tuple(range(n0)) + tuple(func_abs))

        # record types + placeholder replacements in projected positions
        func_types = [None] * len(func_abs)
        for wi, kind, payload in pending:
            if kind == "col":
                k, t = payload
                func_types[k] = t
                self._win_repl[wi] = _PostCol(n0 + k)
            else:
                s_k, c_k, vt = payload
                func_types[s_k] = vt
                func_types[c_k] = INT
                self._win_repl[wi] = _PostAvg(n0 + s_k, n0 + c_k, vt)

        out_cols = list(scope.cols) + [
            ScopeCol(None, None, t) for t in func_types
        ]
        return rel, Scope(out_cols)

    def _rewrite_wins(self, e):
        """Replace _WinRef placeholders with their post-window column refs."""
        if e is None:
            return None
        if isinstance(e, _WinRef):
            return self._win_repl[e.index]
        if isinstance(e, ast.UnaryOp):
            return replace(e, expr=self._rewrite_wins(e.expr))
        if isinstance(e, ast.BinaryOp):
            return replace(
                e, left=self._rewrite_wins(e.left), right=self._rewrite_wins(e.right)
            )
        if isinstance(e, ast.FuncCall):
            return replace(e, args=tuple(self._rewrite_wins(a) for a in e.args))
        if isinstance(e, ast.Cast):
            return replace(e, expr=self._rewrite_wins(e.expr))
        if isinstance(e, ast.Case):
            return ast.Case(
                self._rewrite_wins(e.operand) if e.operand else None,
                tuple(
                    (self._rewrite_wins(c), self._rewrite_wins(r))
                    for c, r in e.whens
                ),
                self._rewrite_wins(e.else_) if e.else_ else None,
            )
        if isinstance(e, ast.Between):
            return replace(
                e,
                expr=self._rewrite_wins(e.expr),
                low=self._rewrite_wins(e.low),
                high=self._rewrite_wins(e.high),
            )
        if isinstance(e, ast.InList):
            return replace(
                e,
                expr=self._rewrite_wins(e.expr),
                items=tuple(self._rewrite_wins(i) for i in e.items),
            )
        if isinstance(e, ast.IsNull):
            return replace(e, expr=self._rewrite_wins(e.expr))
        return e

    def _plan_reduce(self, rel, scope, sel, items, aggs, having):
        """GROUP BY planning: Map(keys+agg args) → Reduce → post scope."""
        # resolve group-by items (ordinals refer to select items pre-extraction)
        group_asts = []
        for g in sel.group_by:
            if isinstance(g, ast.NumberLit) and "." not in g.value:
                n = int(g.value)
                if not (1 <= n <= len(sel.items)):
                    raise PlanError(f"GROUP BY position {n} out of range")
                group_asts.append(sel.items[n - 1].expr)
            else:
                group_asts.append(g)
        key_planned = [self.plan_scalar(g, scope) for g in group_asts]

        # plan aggregate argument expressions + build MirAggregates.
        # DISTINCT aggregates get their own reduce branch over
        # DISTINCT(keys, arg) — the reference plans them the same way
        # (a distinct collection feeding the aggregation); branches join
        # back on the group key below.
        mir_aggs = []
        agg_types = []
        agg_branch: list = []  # parallel to mir_aggs: 0 = main, >0 = distinct
        distinct_branches: list = []  # (branch_id, arg ast)
        post_agg_exprs: list = []  # how each _AggRef is reconstructed post-reduce

        nk = len(group_asts)

        def branch_for(a, v):
            """(branch id, aggregate input expr). min/max/bool_and/bool_or
            over DISTINCT inputs equal their plain forms, so they stay in the
            main branch; other DISTINCT aggs get a dedicated branch whose
            reduce reads the distinct relation's arg column."""
            if not a.distinct or a.name in ("min", "max", "bool_and", "bool_or"):
                return 0, v
            if a.name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
                raise PlanError(f"DISTINCT {a.name} not supported")
            bid = len(distinct_branches) + 1
            distinct_branches.append((bid, v))
            return bid, Column(nk)

        def emit(bid: int, agg) -> int:
            mir_aggs.append(agg)
            agg_branch.append(bid)
            return len(mir_aggs) - 1

        for a in aggs:
            fname = a.name
            if fname == "count":
                # count(*) counts rows; count(x) counts non-null x
                if a.args and not isinstance(a.args[0], ast.Star):
                    arg, _at = self.plan_scalar(a.args[0], scope)
                    bid, arg = branch_for(a, arg)
                else:
                    arg, bid = Literal(1), 0
                i = emit(bid, mir.MirAggregate("count", arg))
                post_agg_exprs.append(("col", i, INT))
                agg_types.append(INT)
            elif fname == "avg":
                v, vt = self.plan_scalar(a.args[0], scope)
                bid, v = branch_for(a, v)
                sum_i = emit(bid, mir.MirAggregate("sum", v))
                # avg divides by the NON-NULL input count
                cnt_i = emit(bid, mir.MirAggregate("count", v))
                post_agg_exprs.append(("avg", (sum_i, cnt_i, vt), FLOAT))
                agg_types.extend([vt, INT])
            elif fname in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
                if a.distinct:
                    raise PlanError(f"DISTINCT {fname} not supported")
                v, vt = self.plan_scalar(a.args[0], scope)
                sum_i = emit(0, mir.MirAggregate("sum", v))
                sq_i = emit(0, mir.MirAggregate("sum", CallBinary("mul", v, v)))
                cnt_i = emit(0, mir.MirAggregate("count", Literal(1)))
                sq_t = PType(ColType.NUMERIC, vt.scale * 2) if vt.col == ColType.NUMERIC else vt
                post_agg_exprs.append((fname, (sum_i, sq_i, cnt_i, vt), FLOAT))
                agg_types.extend([vt, sq_t, INT])
            elif fname == "sum":
                v, vt = self.plan_scalar(a.args[0], scope)
                bid, v = branch_for(a, v)
                sum_i = emit(bid, mir.MirAggregate("sum", v))
                # paired non-null count: sum over only-NULL inputs is NULL
                cnt_i = emit(bid, mir.MirAggregate("count", v))
                post_agg_exprs.append(("sumn", (sum_i, cnt_i, vt), vt))
                agg_types.extend([vt, INT])
            elif fname in _BASIC_AGGS:
                # Basic reduces (reference ReducePlan::Basic): the group's
                # input multiset renders to one value at emission. Output is
                # always STRING (string_agg text; array/list aggs render
                # their pg text form — the engine has no array ADT).
                if a.distinct:
                    raise PlanError(f"DISTINCT {fname} not supported")
                if fname != "string_agg" and len(a.args) != 1:
                    raise PlanError(f"{fname} takes exactly one argument")
                if not a.args:
                    raise PlanError(f"{fname} needs an argument")
                v, vt = self.plan_scalar(a.args[0], scope)
                delim = None
                if fname == "string_agg":
                    if len(a.args) != 2:
                        raise PlanError("string_agg takes (value, delimiter)")
                    if vt.col != ColType.STRING:
                        raise PlanError("string_agg requires a string value")
                    d, dt_ = self.plan_scalar(a.args[1], scope)
                    if not (isinstance(d, Literal) and dt_.col == ColType.STRING):
                        raise PlanError("string_agg delimiter must be a string literal")
                    delim = self.catalog.dict.decode(d.value)
                extra = (delim, _argtype(vt), self.catalog.dict)
                out_t = JSONB if fname == "jsonb_agg" else STRING
                i = emit(0, mir.MirAggregate(fname, v, extra=extra))
                post_agg_exprs.append(("col", i, out_t))
                agg_types.append(out_t)
            elif fname in ("bool_and", "bool_or"):
                # all/any over non-NULL inputs = min/max over the stored
                # int8 truth values (func.rs All/Any accumulation)
                v, _vt = self.plan_scalar(a.args[0], scope)
                i = emit(0, mir.MirAggregate("min" if fname == "bool_and" else "max", v))
                post_agg_exprs.append(("col", i, BOOL))
                agg_types.append(BOOL)
            else:
                v, vt = self.plan_scalar(a.args[0], scope)
                out_t = vt if fname != "count" else INT
                if fname in ("min", "max") and vt.col == ColType.JSONB:
                    raise PlanError(
                        f"{fname} over jsonb is not supported (jsonb has no "
                        "device ordering)"
                    )
                if fname in ("min", "max") and vt.col == ColType.STRING:
                    # device top-1 would rank by dictionary code; route
                    # through the Basic class, which compares decoded strings
                    extra = (None, "str", self.catalog.dict)
                    i = emit(0, mir.MirAggregate(f"{fname}_str", v, extra=extra))
                else:
                    i = emit(0, mir.MirAggregate(fname, v))
                post_agg_exprs.append(("col", i, out_t))
                agg_types.append(out_t)

        # keys become mapped columns so the Reduce's group_key is plain columns
        arity_in = len(scope.cols)
        key_exprs = tuple(p for p, _ in key_planned)
        # aggregate inputs holding string functions (DictFunc) are lifted into
        # mapped columns too: the reduce kernels run under jit, where string
        # tables cannot be evaluated — the eager Mfp stage computes them first
        from ..expr.scalar import expr_has_dictfunc

        lifted: list = []
        for i, ag in enumerate(mir_aggs):
            if expr_has_dictfunc(ag.expr):
                if agg_branch[i] != 0:
                    raise PlanError(
                        "DISTINCT aggregates over string functions not supported"
                    )
                mir_aggs[i] = mir.MirAggregate(
                    ag.func,
                    Column(arity_in + len(key_exprs) + len(lifted)),
                    ag.distinct,
                    ag.extra,
                )
                lifted.append(ag.expr)
        if not distinct_branches:
            inner = mir.MirMap(rel, key_exprs + tuple(lifted))
            rel = mir.MirReduce(
                inner,
                group_key=tuple(range(arity_in, arity_in + len(key_exprs))),
                aggregates=tuple(mir_aggs),
            )
        else:
            if lifted:
                raise PlanError(
                    "string-function aggregates cannot mix with DISTINCT aggregates"
                )
            rel = self._reduce_with_distinct_branches(
                rel, arity_in, key_exprs, mir_aggs, agg_branch, distinct_branches
            )

        # post-reduce scope: keys then aggregate outputs
        post_cols = []
        for gast, (_, t) in zip(group_asts, key_planned):
            name = gast.name if isinstance(gast, ast.Ident) else _default_name(gast)
            qual = gast.qualifier if isinstance(gast, ast.Ident) else None
            post_cols.append(ScopeCol(qual, name, t))
        nkeys = len(post_cols)
        for ag, t in zip(mir_aggs, agg_types):
            post_cols.append(ScopeCol(None, None, t))
        post_scope = Scope(post_cols)

        # rewrite items/having: _AggRef(i) → column ref; group asts → key cols
        self._group_asts = group_asts
        self._post_nkeys = nkeys
        self._post_agg_exprs = post_agg_exprs

        items = [
            ast.SelectItem(self._rewrite_post(it.expr), it.alias) for it in items
        ]
        having = self._rewrite_post(having) if having is not None else None
        return rel, post_scope, items, having

    def _reduce_with_distinct_branches(
        self, rel, arity_in, key_exprs, mir_aggs, agg_branch, distinct_branches
    ):
        """DISTINCT aggregates: one reduce per distinct argument over
        DISTINCT(keys, arg), joined back with the main reduce on the group
        key (NULL-safe: NULL group keys are one group). Output layout is the
        canonical (keys ++ aggregates in declaration order) so the post-agg
        rewrite indices stay valid. Mirrors the reference's distinct-agg
        planning (a distinct collection feeding each such aggregate)."""
        nk = len(key_exprs)
        order: list[int] = []
        per_branch: dict[int, list[int]] = {}
        for i, b in enumerate(agg_branch):
            per_branch.setdefault(b, []).append(i)
        branches = []
        if per_branch.get(0):
            inner = mir.MirMap(rel, key_exprs)
            branches.append(
                mir.MirReduce(
                    inner,
                    group_key=tuple(range(arity_in, arity_in + nk)),
                    aggregates=tuple(mir_aggs[i] for i in per_branch[0]),
                )
            )
            order.append(0)
        for bid, v in distinct_branches:
            inner = mir.MirMap(rel, key_exprs + (v,))
            proj = mir.MirProject(
                inner, tuple(range(arity_in, arity_in + nk + 1))
            )
            branches.append(
                mir.MirReduce(
                    mir.MirDistinct(proj),
                    group_key=tuple(range(nk)),
                    aggregates=tuple(mir_aggs[i] for i in per_branch[bid]),
                )
            )
            order.append(bid)
        if len(branches) == 1:
            return branches[0]
        arities = [nk + len(per_branch[b]) for b in order]
        offsets = [sum(arities[:i]) for i in range(len(arities))]
        equivs = tuple(
            tuple(offsets[j] + k for j in range(len(order)))
            for k in range(nk)
        )
        join = mir.MirJoin(
            inputs=tuple(branches), equivalences=equivs, null_safe=True
        )
        pos: dict[int, int] = {}
        for j, b in enumerate(order):
            for local, i in enumerate(per_branch[b]):
                pos[i] = offsets[j] + nk + local
        out = tuple(range(nk)) + tuple(pos[i] for i in range(len(mir_aggs)))
        return mir.MirProject(join, out)

    def _rewrite_post(self, e):
        """Rewrite a post-aggregation AST: group exprs → _PostCol, aggs → _PostCol/avg."""
        if e is None:
            return None
        for k, g in enumerate(self._group_asts):
            if e == g:
                return _PostCol(k)
        if isinstance(e, _AggRef):
            kind, payload, t = self._post_agg_exprs[e.index]
            if kind == "col":
                return _PostCol(self._post_nkeys + payload)
            if kind == "avg":
                sum_i, cnt_i, vt = payload
                return _PostAvg(self._post_nkeys + sum_i, self._post_nkeys + cnt_i, vt)
            if kind == "sumn":
                sum_i, cnt_i, vt = payload
                return _PostSum(self._post_nkeys + sum_i, self._post_nkeys + cnt_i, vt)
            sum_i, sq_i, cnt_i, vt = payload
            return _PostStat(
                self._post_nkeys + sum_i,
                self._post_nkeys + sq_i,
                self._post_nkeys + cnt_i,
                vt,
                pop=kind in ("stddev_pop", "var_pop"),
                sqrt=kind.startswith("stddev"),
            )
        if isinstance(e, ast.UnaryOp):
            return replace(e, expr=self._rewrite_post(e.expr))
        if isinstance(e, ast.BinaryOp):
            return replace(e, left=self._rewrite_post(e.left), right=self._rewrite_post(e.right))
        if isinstance(e, ast.FuncCall):
            return replace(
                e,
                args=tuple(self._rewrite_post(a) for a in e.args),
                over=_map_window_spec(e.over, self._rewrite_post),
            )
        if isinstance(e, ast.Cast):
            return replace(e, expr=self._rewrite_post(e.expr))
        if isinstance(e, ast.Ident):
            raise PlanError(
                f"column {e.name} must appear in GROUP BY or be used in an aggregate"
            )
        return e


@dataclass(frozen=True)
class _PostCol:
    index: int


@dataclass(frozen=True)
class _PostAvg:
    sum_col: int
    cnt_col: int
    vt: PType


@dataclass(frozen=True)
class _PostSum:
    sum_col: int
    cnt_col: int
    vt: PType


@dataclass(frozen=True)
class _PostStat:
    sum_col: int
    sq_col: int
    cnt_col: int
    vt: PType
    pop: bool
    sqrt: bool


def _to_float(e, t: PType):
    """Cast to float, descaling NUMERIC fixed-point by its scale factor."""
    f = CallUnary("cast_float", e)
    if t.col == ColType.NUMERIC and t.scale:
        f = CallBinary("div", f, Literal(float(10**t.scale), "float32"))
    return f


class _SubqueryLifter:
    """Rewrite uncorrelated subqueries into extra join factors.

    IN (SELECT …)   → join factor Distinct(sub), predicate expr = hidden col
    EXISTS (…)      → cross-join factor Distinct(Map(sub → [1])), predicate TRUE
    scalar (SELECT) → cross-join factor sub (must be single-row), hidden col
    """

    def __init__(self, planner, factors, scopes):
        self.planner = planner
        self.factors = factors
        self.scopes = scopes
        self.n = 0
        # (key_ast | None, PlannedQuery, is_exists) — applied as antijoins
        # after the join is built (NOT IN / NOT EXISTS)
        self.antijoins: list = []
        # equality conjuncts added by decorrelation (joined on in the WHERE)
        self.extra_conjuncts: list = []

    def _add_factor(self, rel, typ: PType) -> ast.Ident:
        name = f"__sub{self.n}"
        self.n += 1
        self.factors.append(rel)
        self.scopes.append(Scope([ScopeCol("__sub", name, typ)]))
        return ast.Ident(name, qualifier="__sub")

    def _add_multi_factor(self, rel, cols: list) -> str:
        """Add a factor with several named columns; returns its qualifier."""
        qual = f"__subq{self.n}"
        self.n += 1
        self.factors.append(rel)
        self.scopes.append(Scope([ScopeCol(qual, n, t) for n, t in cols]))
        return qual

    def _decorrelate_scalar(self, q: ast.Query):
        """Decorrelate `(SELECT agg-expr FROM … WHERE inner = outer AND …)`.

        The classic equality pattern (reference: HIR→MIR decorrelation,
        src/sql/src/plan/lowering.rs): rewrite to a grouped subquery over the
        correlation keys and join it on them. Missing groups drop the outer
        row (consistent with WHERE-context NULL comparisons; this engine has
        no NULLs).
        """
        if q.ctes or q.order_by or q.limit is not None:
            raise PlanError("unsupported correlated subquery shape")
        sel = q.body
        if not isinstance(sel, ast.Select) or sel.group_by or sel.having or len(sel.items) != 1:
            raise PlanError("unsupported correlated subquery shape")
        # inner alias universe (syntactic correlation detection)
        inner_names: set = set()
        def collect(f):
            if isinstance(f, ast.TableRef):
                inner_names.add(f.alias or f.name)
            elif isinstance(f, ast.JoinClause):
                collect(f.left)
                collect(f.right)
            elif isinstance(f, ast.SubqueryRef):
                inner_names.add(f.alias)
        for f in sel.from_:
            collect(f)

        def is_inner(i: ast.Ident) -> bool:
            return i.qualifier is not None and i.qualifier in inner_names

        corr: list[tuple[ast.Ident, ast.Ident]] = []  # (inner, outer)
        residual: list = []
        for c in _split_and(sel.where) if sel.where is not None else []:
            if (
                isinstance(c, ast.BinaryOp) and c.op == "="
                and isinstance(c.left, ast.Ident) and isinstance(c.right, ast.Ident)
                and is_inner(c.left) != is_inner(c.right)
            ):
                inner, outer = (c.left, c.right) if is_inner(c.left) else (c.right, c.left)
                corr.append((inner, outer))
                continue
            residual.append(c)
        if not corr:
            raise PlanError("correlated subquery: no equality correlation found")
        res_where = None
        for c in residual:
            res_where = c if res_where is None else ast.BinaryOp("and", res_where, c)
        items = tuple(
            ast.SelectItem(inner, alias=f"__ck{i}") for i, (inner, _o) in enumerate(corr)
        ) + (ast.SelectItem(sel.items[0].expr, alias="__agg"),)
        dq = ast.Query(
            ast.Select(
                items=items,
                from_=sel.from_,
                where=res_where,
                group_by=tuple(inner for inner, _o in corr),
            )
        )
        pq = self.planner.plan_query(dq)
        qual = self._add_multi_factor(
            pq.mir, [(c.name, c.typ) for c in pq.scope.cols]
        )
        names = [c.name for c in pq.scope.cols]
        for i, (_inner, outer) in enumerate(corr):
            self.extra_conjuncts.append(
                ast.BinaryOp("=", outer, ast.Ident(names[i], qualifier=qual))
            )
        return ast.Ident(names[-1], qualifier=qual)

    def rewrite_conjunct(self, e):
        """Rewrite a top-level WHERE/ON conjunct; antijoins allowed here."""
        return self.rewrite(e, _allow_anti=True)

    def rewrite(self, e, _allow_anti: bool = False):
        if e is None or isinstance(
            e,
            (ast.NumberLit, ast.StringLit, ast.BoolLit, ast.NullLit, ast.DateLit,
             ast.Ident, ast.Star),
        ):
            return e
        if isinstance(e, ast.Subquery):
            try:
                pq = self.planner.plan_query(e.query)
            except PlanError as err:
                if not e.exists and "unknown column" in str(err):
                    # correlated scalar subquery: try equality decorrelation
                    return self._decorrelate_scalar(e.query)
                raise
            if e.exists:
                one = mir.MirProject(
                    mir.MirMap(pq.mir, (Literal(1),)),
                    (len(pq.scope.cols),),
                )
                ident = self._add_factor(mir.MirDistinct(one), INT)
                return ast.BoolLit(True)  # presence enforced by the join itself
            if len(pq.scope.cols) != 1:
                raise PlanError("scalar subquery must return one column")
            return self._add_factor(pq.mir, pq.scope.cols[0].typ)
        if isinstance(e, ast.InList):
            subs = [i for i in e.items if isinstance(i, ast.Subquery)]
            if subs:
                if len(e.items) != 1:
                    raise PlanError("IN mixing subquery and literals unsupported")
                pq = self.planner.plan_query(subs[0].query)
                if len(pq.scope.cols) != 1:
                    raise PlanError("IN subquery must return one column")
                if e.negated:
                    if not _allow_anti:
                        raise PlanError(
                            "NOT IN (SELECT …) only supported as a top-level "
                            "WHERE/ON conjunct"
                        )
                    # antijoin: handled at relation level after the join builds
                    self.antijoins.append((self.rewrite(e.expr), pq, False))
                    return ast.BoolLit(True)
                ident = self._add_factor(
                    mir.MirDistinct(pq.mir), pq.scope.cols[0].typ
                )
                return ast.BinaryOp("=", self.rewrite(e.expr), ident)
            return replace(e, expr=self.rewrite(e.expr),
                           items=tuple(self.rewrite(i) for i in e.items))
        if isinstance(e, ast.UnaryOp):
            if (
                e.op == "not"
                and isinstance(e.expr, ast.Subquery)
                and e.expr.exists
            ):
                if not _allow_anti:
                    raise PlanError(
                        "NOT EXISTS only supported as a top-level WHERE/ON conjunct"
                    )
                pq = self.planner.plan_query(e.expr.query)
                self.antijoins.append((None, pq, True))
                return ast.BoolLit(True)
            return replace(e, expr=self.rewrite(e.expr))
        if isinstance(e, ast.BinaryOp):
            return replace(e, left=self.rewrite(e.left), right=self.rewrite(e.right))
        if isinstance(e, ast.FuncCall):
            return replace(e, args=tuple(self.rewrite(a) for a in e.args))
        if isinstance(e, ast.Cast):
            return replace(e, expr=self.rewrite(e.expr))
        if isinstance(e, ast.Between):
            return replace(
                e, expr=self.rewrite(e.expr), low=self.rewrite(e.low),
                high=self.rewrite(e.high),
            )
        if isinstance(e, ast.IsNull):
            return replace(e, expr=self.rewrite(e.expr))
        if isinstance(e, ast.Case):
            return ast.Case(
                self.rewrite(e.operand) if e.operand else None,
                tuple((self.rewrite(c), self.rewrite(r)) for c, r in e.whens),
                self.rewrite(e.else_) if e.else_ else None,
            )
        return e


def _join_and(parts):
    out = None
    for p_ in parts:
        out = p_ if out is None else ast.BinaryOp("and", out, p_)
    return out


def _split_and(e):
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _is_mz_now(e) -> bool:
    return isinstance(e, ast.FuncCall) and e.name == "mz_now"


def _contains_mz_now(e) -> bool:
    if _is_mz_now(e):
        return True
    if isinstance(e, ast.BinaryOp):
        return _contains_mz_now(e.left) or _contains_mz_now(e.right)
    if isinstance(e, ast.UnaryOp):
        return _contains_mz_now(e.expr)
    if isinstance(e, ast.FuncCall):
        return any(_contains_mz_now(a) for a in e.args)
    if isinstance(e, ast.Cast):
        return _contains_mz_now(e.expr)
    if isinstance(e, (ast.Between,)):
        return _contains_mz_now(e.expr) or _contains_mz_now(e.low) or _contains_mz_now(e.high)
    return False


def _default_name(e) -> str:
    if isinstance(e, ast.Ident):
        return e.name
    if isinstance(e, ast.FuncCall):
        return e.name
    if isinstance(e, _AggRef):
        return "agg"
    return "column"


def _apply_finishing_as_topk(pq: PlannedQuery):
    """LIMIT inside a view body becomes a TopK (global group).

    Rejected for STRING order columns when rows are actually dropped
    (LIMIT/OFFSET): a maintained TopK ranks rows on device by dictionary
    code (insertion order, not collation), which would silently mis-order.
    Without LIMIT/OFFSET the TopK keeps every row, so ordering is
    semantically inert (relations are unordered) and stays allowed. One-shot
    peeks are unaffected — their finishing sorts decoded strings host-side
    (coordinator._finish)."""
    if pq.finishing.limit is not None or pq.finishing.offset:
        for col, _desc in pq.finishing.order_by:
            if pq.scope.cols[col].typ.col in (ColType.STRING, ColType.JSONB):
                raise PlanError(
                    "ORDER BY on a string column with LIMIT is not supported "
                    "in maintained views (device ordering is by dictionary "
                    "code)"
                )
    return mir.MirTopK(
        pq.mir,
        group_key=(),
        order_by=tuple(pq.finishing.order_by),
        limit=pq.finishing.limit,
        offset=pq.finishing.offset,
        nulls_last=tuple(pq.finishing.nulls_last) or None,
    )
