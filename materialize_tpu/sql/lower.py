"""MIR → LIR lowering: produce a renderable DataflowDescription.

The analogue of the reference's plan lowering
(src/compute-types/src/plan/lowering.rs:136): Map/Filter/Project chains fuse
into single MFPs, joins take their physical plan from the
JoinImplementation transform, reduces split into accumulable and
hierarchical parts (collation via a join of partial reduces, mirroring
ReducePlan::Collation, src/compute-types/src/plan/reduce.rs:386).
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from ..dataflow import BuildDesc, DataflowDescription
from ..dataflow import plan as lir
from ..expr import relation as mir
from ..expr.linear import MapFilterProject, MfpBuilder, substitute_columns
from ..expr.scalar import CallBinary, CallUnary, Column, Literal
from ..ops.reduce import AggregateExpr
from ..ops.topk import TopKPlan
from ..transform.join_implementation import plan_join_implementation

I64 = np.dtype(np.int64)
F32 = np.dtype(np.float32)


class Lowerer:
    def __init__(self, dtypes_env: dict, mono_ids: set | None = None):
        self.env = dict(dtypes_env)
        self.mono_ids = set(mono_ids or ())

    # -- dtype inference ------------------------------------------------------
    def dtypes(self, e) -> tuple:
        if isinstance(e, mir.MirGet):
            return tuple(self.env[e.id])
        if isinstance(e, mir.MirConstant):
            return tuple(e.dtypes)
        if isinstance(e, mir.MirMap):
            base = list(self.dtypes(e.input))
            for ex in e.exprs:
                base.append(_expr_np_dtype(ex, base))
            return tuple(base)
        if isinstance(e, mir.MirFilter):
            return self.dtypes(e.input)
        if isinstance(e, mir.MirProject):
            base = self.dtypes(e.input)
            return tuple(base[i] for i in e.outputs)
        if isinstance(e, mir.MirJoin):
            out = []
            for i in e.inputs:
                out.extend(self.dtypes(i))
            return tuple(out)
        if isinstance(e, mir.MirReduce):
            base = self.dtypes(e.input)
            out = [base[i] for i in e.group_key]
            for a in e.aggregates:
                if a.func == "count":
                    out.append(I64)
                elif a.func in ("string_agg", "array_agg", "list_agg",
                                "jsonb_agg", "min_str", "max_str"):
                    out.append(I64)  # rendered string code
                else:
                    out.append(_expr_np_dtype(a.expr, list(base)))
            return tuple(out)
        if isinstance(e, mir.MirTopK):
            return self.dtypes(e.input)
        if isinstance(e, mir.MirWindow):
            base = self.dtypes(e.input)
            return tuple(base) + tuple(
                _window_out_dtype(f, base) for f in e.funcs
            )
        if isinstance(e, (mir.MirNegate, mir.MirThreshold, mir.MirDistinct)):
            return self.dtypes(e.input)
        if isinstance(e, mir.MirUnion):
            return self.dtypes(e.inputs[0])
        if isinstance(e, mir.MirLetRec):
            for gid, dts, _b in e.bindings:
                self.env[gid] = tuple(dts)
            return self.dtypes(e.body)
        if isinstance(e, mir.MirTemporalFilter):
            return self.dtypes(e.input)
        if isinstance(e, mir.MirFlatMap):
            return self.dtypes(e.input) + (I64,)
        raise TypeError(f"dtypes: {type(e).__name__}")

    # -- lowering -------------------------------------------------------------
    def lower(self, e):
        """MIR expr → LIR expr."""
        # fuse M/F/P chains into one MFP over the chain's base
        if isinstance(e, (mir.MirMap, mir.MirFilter, mir.MirProject)):
            chain = []
            base = e
            while isinstance(base, (mir.MirMap, mir.MirFilter, mir.MirProject)):
                chain.append(base)
                base = base.input
            b = MfpBuilder(mir.arity(base))
            for node in reversed(chain):
                if isinstance(node, mir.MirMap):
                    b.add_maps(node.exprs)
                elif isinstance(node, mir.MirFilter):
                    b.add_predicates(node.predicates)
                else:
                    b.project(node.outputs)
            mfp = b.finish()
            lowered = self.lower(base)
            if mfp.is_identity():
                return lowered
            return lir.Mfp(lowered, mfp)
        if isinstance(e, mir.MirGet):
            return lir.Get(e.id)
        if isinstance(e, mir.MirConstant):
            rows = tuple((data, 0, diff) for data, diff in e.rows)
            return lir.Constant(rows, tuple(e.dtypes))
        if isinstance(e, mir.MirJoin):
            impl = e.implementation or plan_join_implementation(e)
            inputs = tuple(self.lower(i) for i in e.inputs)
            # SQL equality never matches NULLs, but the in-band sentinel
            # representation would (sentinel == sentinel); guard every
            # equivalence column with IS NOT NULL in the join closure
            # (the reference's join planning likewise hoists non-null
            # constraints from equivalences, lowering.rs)
            guard_cols = (
                []
                if e.null_safe
                else sorted({g for cls in e.equivalences for g in cls})
            )

            def res_eq(a, c):
                if not e.null_safe:
                    return CallBinary("eq", Column(a), Column(c))
                # IS NOT DISTINCT FROM: NULL matches NULL in null-safe joins
                from ..expr.scalar import CallVariadic

                return CallVariadic(
                    "or",
                    (
                        CallBinary("eq", Column(a), Column(c)),
                        CallBinary(
                            "and",
                            CallUnary("is_null", Column(a)),
                            CallUnary("is_null", Column(c)),
                        ),
                    ),
                )

            preds = tuple(
                CallUnary("is_not_null", Column(c)) for c in guard_cols
            ) + tuple(
                res_eq(a, c) for a, c in impl.residual_equalities
            )
            closure = None
            if preds:
                total = sum(mir.arity(i) for i in e.inputs)
                b = MfpBuilder(total)
                b.add_predicates(preds)
                closure = b.finish()
            return lir.Join(inputs=inputs, plan=impl.lir_plan, closure=closure)
        if isinstance(e, mir.MirReduce):
            return self.lower_reduce(e)
        if isinstance(e, mir.MirTopK):
            from ..transform.monotonic import is_monotonic

            return lir.TopK(
                self.lower(e.input),
                TopKPlan(
                    group_cols=tuple(e.group_key),
                    order_by=tuple(e.order_by),
                    limit=e.limit,
                    offset=e.offset,
                    nulls_last=e.nulls_last,
                ),
                monotonic=is_monotonic(e.input, self.mono_ids),
            )
        if isinstance(e, mir.MirWindow):
            from ..ops.window import WindowFuncSpec, WindowPlan

            base = self.dtypes(e.input)
            funcs = tuple(
                WindowFuncSpec(
                    func=f.func,
                    arg=f.arg,
                    offset=f.offset,
                    out_dtype=_window_out_dtype(f, base).name,
                )
                for f in e.funcs
            )
            return lir.Window(
                self.lower(e.input),
                WindowPlan(
                    partition_cols=tuple(e.partition_cols),
                    order_by=tuple(e.order_by),
                    funcs=funcs,
                    nulls_last=e.nulls_last,
                ),
            )
        if isinstance(e, mir.MirNegate):
            return lir.Negate(self.lower(e.input))
        if isinstance(e, mir.MirThreshold):
            return lir.Threshold(self.lower(e.input))
        if isinstance(e, mir.MirDistinct):
            n = mir.arity(e.input)
            return lir.Reduce(
                self.lower(e.input), key_cols=tuple(range(n)), distinct=True
            )
        if isinstance(e, mir.MirUnion):
            return lir.Union(tuple(self.lower(i) for i in e.inputs))
        if isinstance(e, mir.MirTemporalFilter):
            return lir.TemporalFilter(
                self.lower(e.input), tuple(e.lowers), tuple(e.uppers)
            )
        if isinstance(e, mir.MirFlatMap):
            return lir.FlatMap(self.lower(e.input), e.func, tuple(e.exprs))
        if isinstance(e, mir.MirLetRec):
            rec_ids = set()
            for gid, dts, _b in e.bindings:
                self.env[gid] = tuple(dts)
                rec_ids.add(gid)
            bindings = tuple(
                (gid, self.lower(b), tuple(dts)) for gid, dts, b in e.bindings
            )
            body = self.lower(e.body)
            refs = set()
            for _g, _d, b in e.bindings:
                refs |= mir.collect_get_ids(b)
            refs |= mir.collect_get_ids(e.body)
            ext = tuple(sorted(refs - rec_ids))
            return lir.LetRec(
                bindings=bindings,
                body=body,
                body_dtypes=self.dtypes(e.body),
                external_ids=ext,
                ext_dtypes=tuple((g, tuple(self.env[g])) for g in ext),
            )
        raise TypeError(f"lower: {type(e).__name__}")

    def lower_reduce(self, e: mir.MirReduce):
        result = self._lower_reduce_inner(e)
        if e.group_key or not e.aggregates:
            return result
        return self._with_default_row(result, e)

    def _with_default_row(self, result, e: mir.MirReduce):
        """Global (no GROUP BY) aggregates return one default row over empty
        input: count → 0, sum accumulators → 0 (the paired-count post guard
        turns them into NULL), min/max → the NULL sentinel directly. The
        reference's reduce lowering unions a default row minus an existence
        marker (lowering.rs empty-key pattern):

            result ∪ π_aggs(default − (default ⋈ marker))

        where marker is DISTINCT over a constant column of result (nonempty
        iff result is), so exactly one branch survives.
        """
        from ..expr.scalar import null_sentinel

        n = len(e.aggregates)
        out_dtypes = self.dtypes(e)
        defaults = tuple(
            null_sentinel(dt)
            if a.func in ("min", "max", "string_agg", "array_agg", "list_agg",
                          "jsonb_agg", "min_str", "max_str")
            else (0 if np.issubdtype(dt, np.integer) else np.float32(0.0))
            for a, dt in zip(e.aggregates, out_dtypes)
        )
        b = MfpBuilder(n)
        b.add_maps((Literal(1),))
        b.project((n,))
        marker = lir.Reduce(lir.Mfp(result, b.finish()), key_cols=(0,), distinct=True)
        default_marked = lir.Constant(
            rows=(((1,) + defaults, 0, 1),), dtypes=(I64,) + tuple(out_dtypes)
        )
        jb = MfpBuilder(2 + n)
        jb.project(tuple(range(1 + n)))
        joined = lir.Join(
            inputs=(default_marked, marker),
            plan=lir.LinearJoinPlan(
                stages=(lir.JoinStage(stream_key=(0,), lookup_key=(0,)),)
            ),
            closure=jb.finish(),
        )
        anti = lir.Union((default_marked, lir.Negate(joined)))
        db = MfpBuilder(1 + n)
        db.project(tuple(range(1, 1 + n)))
        return lir.Union((result, lir.Mfp(anti, db.finish())))

    def _lower_reduce_inner(self, e: mir.MirReduce):
        """Split aggregates into accumulable and hierarchical parts.

        Mirrors ReducePlan construction (plan/reduce.rs:130): Accumulable for
        sum/count, Hierarchical (top-1 kernel) for min/max, Collation (a join
        of the partial reduces on the group key) when mixed.
        """
        in_dtypes = list(self.dtypes(e.input))
        key = tuple(e.group_key)
        if not e.aggregates:
            return lir.Reduce(self.lower(e.input), key_cols=key, distinct=True)

        parts = []  # (agg_indices, lir builder fn)
        _BASIC = (
            "string_agg", "array_agg", "list_agg", "jsonb_agg",
            "min_str", "max_str",
        )
        acc_idx = [i for i, a in enumerate(e.aggregates) if a.func in ("sum", "count")]
        hier_idx = [i for i, a in enumerate(e.aggregates) if a.func in ("min", "max")]
        basic_idx = [i for i, a in enumerate(e.aggregates) if a.func in _BASIC]
        unknown = [
            a.func
            for a in e.aggregates
            if a.func not in ("sum", "count", "min", "max") + _BASIC
        ]
        if unknown:
            raise NotImplementedError(f"aggregates {unknown}")

        lowered_in = self.lower(e.input)

        def accumulable_part():
            aggs = []
            for i in acc_idx:
                a = e.aggregates[i]
                if a.func == "count":
                    # keep the argument: count(x) skips NULL inputs
                    aggs.append(AggregateExpr("count", a.expr))
                else:
                    dt = _expr_np_dtype(a.expr, in_dtypes)
                    if dt == F32:
                        # float sums accumulate in i64 fixed point so
                        # retractions cancel exactly (ops/reduce.py
                        # AggregateExpr docstring; reference Accum::Float)
                        from ..ops.reduce import FLOAT_FIXED_SCALE

                        aggs.append(
                            AggregateExpr(
                                "sum", a.expr, "int64",
                                fixed_scale=FLOAT_FIXED_SCALE,
                            )
                        )
                    else:
                        aggs.append(AggregateExpr("sum", a.expr, "int64"))
            return lir.Reduce(lowered_in, key_cols=key, aggs=tuple(aggs))

        def hierarchical_part(agg_i: int):
            a = e.aggregates[agg_i]
            n_in = len(in_dtypes)
            # materialize the agg expr as a column, top-1 it per group
            b = MfpBuilder(n_in)
            b.add_maps((a.expr,))
            b.project(tuple(key) + (n_in,))
            pre = lir.Mfp(lowered_in, b.finish())
            nk = len(key)
            from ..transform.monotonic import is_monotonic

            topk = lir.TopK(
                pre,
                TopKPlan(
                    group_cols=tuple(range(nk)),
                    order_by=((nk, a.func == "max"),),
                    limit=1,
                    # NULL inputs never win min/max, but an all-NULL group
                    # still yields its (NULL) row (SQL aggregate semantics)
                    nulls_last=(True,),
                ),
                monotonic=is_monotonic(e.input, self.mono_ids),
            )
            return topk

        def basic_part(agg_i: int):
            # ReducePlan::Basic: materialize (keys, element) and hand the
            # multiset to the BasicAgg host operator (render/reduce.rs:196)
            a = e.aggregates[agg_i]
            n_in = len(in_dtypes)
            b = MfpBuilder(n_in)
            b.add_maps((a.expr,))
            b.project(tuple(key) + (n_in,))
            pre = lir.Mfp(lowered_in, b.finish())
            nk = len(key)
            return lir.BasicAgg(
                pre, key_cols=tuple(range(nk)), func=a.func, extra=a.extra
            )

        if acc_idx and not hier_idx and not basic_idx:
            return accumulable_part()
        if len(hier_idx) == 1 and not acc_idx and not basic_idx:
            return hierarchical_part(hier_idx[0])
        if len(basic_idx) == 1 and not acc_idx and not hier_idx:
            return basic_part(basic_idx[0])
        # collation: join partial reduces on the group key
        partials = []  # (lir expr, agg indices, out arity)
        if acc_idx:
            partials.append((accumulable_part(), acc_idx))
        for hi in hier_idx:
            partials.append((hierarchical_part(hi), [hi]))
        for bi in basic_idx:
            partials.append((basic_part(bi), [bi]))
        nk = len(key)
        # every partial outputs (key cols ++ its agg cols)
        stages = []
        arities = [nk + len(p[1]) for p in partials]
        for i in range(1, len(partials)):
            prior = sum(arities[:i])
            stages.append(
                lir.JoinStage(
                    stream_key=tuple(range(nk)),
                    lookup_key=tuple(range(nk)),
                )
            )
        # closure: project canonical (keys, aggs in declaration order)
        total = sum(arities)
        pos_of_agg: dict[int, int] = {}
        off = 0
        for part_expr, idxs in partials:
            for j, agg_i in enumerate(idxs):
                pos_of_agg[agg_i] = off + nk + j
            off += nk + len(idxs)
        proj = tuple(range(nk)) + tuple(
            pos_of_agg[i] for i in range(len(e.aggregates))
        )
        b = MfpBuilder(total)
        b.project(proj)
        return lir.Join(
            inputs=tuple(p[0] for p in partials),
            plan=lir.LinearJoinPlan(stages=tuple(stages)),
            closure=b.finish(),
        )


def _window_out_dtype(f, in_dtypes) -> np.dtype:
    """np dtype of one window function's output column."""
    if f.func in ("row_number", "rank", "dense_rank", "ntile", "count"):
        return I64
    dt = np.dtype(in_dtypes[f.arg])
    if dt == np.bool_:
        dt = np.dtype(np.int8)
    if f.func == "sum":
        return F32 if dt == F32 else I64
    return dt


def _expr_np_dtype(expr, col_dtypes):
    from ..dataflow.runtime import _expr_dtype

    return _expr_dtype(expr, col_dtypes)


def lower_to_dataflow(
    obj_id: str,
    mir_expr,
    dtypes_env: dict,
    source_ids: list[str],
    index_key: tuple = (),
    as_of: int = 0,
    mono_ids: set | None = None,
    until: int | None = None,
) -> DataflowDescription:
    """Build a one-object DataflowDescription for `mir_expr`."""
    lo = Lowerer(dtypes_env, mono_ids)
    plan = lo.lower(mir_expr)
    out_dtypes = lo.dtypes(mir_expr)
    return DataflowDescription(
        source_imports={sid: tuple(dtypes_env[sid]) for sid in source_ids},
        objects_to_build=[BuildDesc(obj_id, plan, out_dtypes)],
        index_exports={f"idx_{obj_id}": (obj_id, tuple(index_key))},
        as_of=as_of,
        until=until,
    )
