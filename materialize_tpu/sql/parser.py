"""Recursive-descent SQL parser.

The analogue of the reference's hand-written parser (`mz-sql-parser`,
doc/developer/life-of-a-query.md:104-112 notes it's a recursive-descent
PostgreSQL-dialect fork). Precedence follows PostgreSQL:
  OR < AND < NOT < comparison < IS/BETWEEN/IN/LIKE < + - < * / % < unary - < :: .
"""

from __future__ import annotations

from typing import Optional

from . import ast
from .lexer import Token, lex


class ParseError(ValueError):
    pass


class Parser:
    def __init__(self, sql: str):
        self.toks = lex(sql)
        self.i = 0

    # -- token helpers -------------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "KW" and t.value in words

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            raise ParseError(f"expected {word.upper()}, found {self.peek().value!r}")

    def at_op(self, op: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value == op

    def eat_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise ParseError(f"expected {op!r}, found {self.peek().value!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "IDENT" or t.kind == "KW":
            self.next()
            return t.value
        raise ParseError(f"expected identifier, found {t.value!r}")

    # -- entry ----------------------------------------------------------------
    def parse_statement(self):
        if self.at_kw("select", "with", "values") or self.at_op("("):
            return ast.SelectStatement(self.parse_query())
        if self.at_kw("create"):
            return self.parse_create()
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("delete"):
            return self.parse_delete()
        if self.at_kw("update"):
            return self.parse_update()
        if self.at_kw("explain"):
            return self.parse_explain()
        if self.at_kw("show"):
            return self.parse_show()
        if self.at_kw("drop"):
            return self.parse_drop()
        if self.at_kw("alter"):
            self.next()
            self.expect_kw("system")
            self.expect_kw("set")
            name = self.ident()
            self.expect_op("=")
            t = self.next()
            return ast.SetVariable(name, t.value, system=True)
        if self.at_kw("set"):
            self.next()
            name = self.ident()
            if self.eat_kw("to"):
                pass
            else:
                self.expect_op("=")
            t = self.next()
            return ast.SetVariable(name, t.value, system=False)
        if self.at_kw("reset") or (
            self.peek().kind == "IDENT" and self.peek().value == "reset"
        ):
            self.next()
            return ast.ResetVariable(self.ident())
        if self.peek().kind == "IDENT" and self.peek().value == "copy":
            self.next()
            if self.eat_op("("):
                q = self.parse_query()
                self.expect_op(")")
            else:
                name = self.ident()
                q = ast.Query(
                    ast.Select(
                        items=(ast.SelectItem(ast.Star()),),
                        from_=(ast.TableRef(name),),
                    )
                )
            self.expect_kw("to")
            target = self.ident()
            if target != "stdout":
                raise ParseError("only COPY … TO STDOUT is supported")
            fmt = "csv"
            if self.eat_kw("with"):
                self.expect_op("(")
                self.ident()  # format
                fmt = self.ident()
                self.expect_op(")")
            return ast.Copy(q, fmt)
        if self.at_kw("subscribe"):
            self.next()
            self.eat_kw("to")
            if self.at_op("("):
                self.next()
                q = self.parse_query()
                self.expect_op(")")
            else:
                name = self.ident()
                q = ast.Query(
                    ast.Select(
                        items=(ast.SelectItem(ast.Star()),),
                        from_=(ast.TableRef(name),),
                    )
                )
            snapshot, progress = True, False
            if self.eat_kw("with"):
                self.expect_op("(")
                while not self.at_op(")"):
                    opt = self.ident().lower()
                    if opt == "snapshot":
                        snapshot = True
                        if self.at_kw("true") or self.at_kw("false"):
                            snapshot = self.next().value == "true"
                    elif opt == "progress":
                        progress = True
                    else:
                        raise ParseError(f"unknown SUBSCRIBE option {opt!r}")
                    self.eat_op(",")
                self.expect_op(")")
            return ast.Subscribe(q, snapshot=snapshot, progress=progress)
        raise ParseError(f"unsupported statement start: {self.peek().value!r}")

    # -- DDL ------------------------------------------------------------------
    def parse_create(self):
        self.expect_kw("create")
        if self.eat_kw("table"):
            name = self.ident()
            self.expect_op("(")
            cols = []
            while True:
                cname = self.ident()
                ctyp = self.parse_type_name()
                not_null = False
                if self.eat_kw("not"):
                    self.expect_kw("null")
                    not_null = True
                cols.append(ast.ColumnDef(cname, ctyp, not_null))
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            return ast.CreateTable(name, tuple(cols))
        if self.eat_kw("source"):
            name = self.ident()
            columns = []
            if self.eat_op("("):
                while not self.at_op(")"):
                    cname = self.ident()
                    ctyp = self.parse_type_name()
                    columns.append(ast.ColumnDef(cname, ctyp))
                    self.eat_op(",")
                self.expect_op(")")
            self.expect_kw("from")
            if self.peek().kind == "IDENT" and self.peek().value == "file":
                return self._parse_file_source(name, tuple(columns))
            if columns:
                raise ParseError(
                    "column lists are only supported on FILE sources"
                )
            self.expect_kw("load")
            self.expect_kw("generator")
            gen = self.ident()
            if gen == "key" and self.peek().value == "value":
                self.next()
                gen = "key_value"
            options = []
            if self.eat_op("("):
                while not self.at_op(")"):
                    key = self.ident()
                    while self.peek().kind in ("KW", "IDENT") and not self.at_op(","):
                        nxt = self.peek()
                        if nxt.kind in ("KW", "IDENT"):
                            key += " " + self.next().value
                        else:
                            break
                        if self.peek().kind in ("NUMBER", "STRING"):
                            break
                    val = None
                    t = self.peek()
                    if t.kind in ("NUMBER", "STRING"):
                        val = self.next().value
                    options.append((key, val))
                    self.eat_op(",")
                self.expect_op(")")
            return ast.CreateSource(name, gen, tuple(options))
        if self.eat_kw("sink"):
            name = self.ident()
            self.expect_kw("from")
            from_name = self.ident()
            self.expect_kw("into")
            if self.ident().lower() != "file":
                raise ParseError("only CREATE SINK … INTO FILE is supported")
            t = self.peek()
            if t.kind != "STRING":
                raise ParseError(f"expected file path string, found {t.value!r}")
            path = self.next().value
            fmt = "json"
            if self.peek().kind == "IDENT" and self.peek().value == "format":
                self.next()
                fmt = self.ident().lower()
            if fmt not in ("json", "csv"):
                raise ParseError(f"unsupported sink format {fmt!r}")
            return ast.CreateSink(name, from_name, path, fmt)
        if self.eat_kw("materialized"):
            self.expect_kw("view")
            name = self.ident()
            self.expect_kw("as")
            return ast.CreateMaterializedView(name, self.parse_query())
        if self.eat_kw("view"):
            name = self.ident()
            self.expect_kw("as")
            return ast.CreateView(name, self.parse_query())
        if self.eat_kw("default"):
            self.expect_kw("index")
            self.expect_kw("on")
            return ast.CreateIndex(None, self.ident(), ())
        if self.eat_kw("index"):
            name = None
            if not self.at_kw("on"):
                name = self.ident()
            self.expect_kw("on")
            on = self.ident()
            cols = []
            if self.eat_op("("):
                while not self.at_op(")"):
                    cols.append(self.ident())
                    self.eat_op(",")
                self.expect_op(")")
            return ast.CreateIndex(name, on, tuple(cols))
        raise ParseError(f"unsupported CREATE {self.peek().value!r}")

    def _parse_file_source(self, name: str, columns: tuple):
        self.next()  # 'file'
        t = self.peek()
        if t.kind != "STRING":
            raise ParseError(f"expected file path string, found {t.value!r}")
        path = self.next().value
        fmt = "json"
        if self.eat_op("("):
            while not self.at_op(")"):
                key = self.ident().lower()
                if key == "format":
                    fmt = self.ident().lower()
                else:
                    raise ParseError(f"unknown file source option {key!r}")
                self.eat_op(",")
            self.expect_op(")")
        if fmt not in ("json", "csv", "avro"):
            raise ParseError(f"unsupported file source format {fmt!r}")
        envelope, key_cols = "none", ()
        if self.peek().kind == "IDENT" and self.peek().value == "envelope":
            self.next()
            env = self.ident().lower()
            if env != "upsert":
                raise ParseError(f"unsupported envelope {env!r}")
            envelope = "upsert"
            if self.eat_op("("):
                kw = self.ident().lower()
                if kw != "key":
                    raise ParseError("expected KEY (cols) in ENVELOPE UPSERT")
                self.expect_op("(")
                cols = []
                while not self.at_op(")"):
                    cols.append(self.ident())
                    self.eat_op(",")
                self.expect_op(")")
                self.expect_op(")")
                key_cols = tuple(cols)
        if not columns:
            raise ParseError("file sources require an explicit column list")
        return ast.CreateFileSource(name, columns, path, fmt, envelope, key_cols)

    def parse_type_name(self) -> str:
        base = self.ident()
        # numeric(p, s), varchar(n) — swallow parenthesized params
        if self.eat_op("("):
            while not self.at_op(")"):
                self.next()
            self.expect_op(")")
        # timestamp with time zone
        while self.peek().kind in ("KW", "IDENT") and self.peek().value in (
            "with", "without", "time", "zone", "precision", "varying",
        ):
            base += " " + self.next().value
        return base

    # -- DML ------------------------------------------------------------------
    def parse_insert(self):
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.ident()
        cols = []
        if self.at_op("("):
            self.next()
            while not self.at_op(")"):
                cols.append(self.ident())
                self.eat_op(",")
            self.expect_op(")")
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = []
            while not self.at_op(")"):
                row.append(self.parse_expr())
                self.eat_op(",")
            self.expect_op(")")
            rows.append(tuple(row))
            if not self.eat_op(","):
                break
        return ast.Insert(table, tuple(cols), tuple(rows))

    def parse_delete(self):
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self.ident()
        where = self.parse_expr() if self.eat_kw("where") else None
        return ast.Delete(table, where)

    def parse_update(self):
        self.expect_kw("update")
        table = self.ident()
        self.expect_kw("set")
        assignments = []
        while True:
            col = self.ident()
            self.expect_op("=")
            assignments.append((col, self.parse_expr()))
            if not self.eat_op(","):
                break
        where = self.parse_expr() if self.eat_kw("where") else None
        return ast.Update(table, tuple(assignments), where)

    def parse_explain(self):
        self.expect_kw("explain")
        stage = "optimized"
        if self.peek().kind == "IDENT" and self.peek().value == "timestamp":
            self.next()
            self.eat_kw("for")
            return ast.Explain("timestamp", self.parse_statement())
        if self.peek().kind == "IDENT" and self.peek().value == "timeline":
            # EXPLAIN TIMELINE <stmt>: run it and render the span tree
            self.next()
            self.eat_kw("for")
            return ast.Explain("timeline", self.parse_statement())
        if self.peek().kind == "IDENT" and self.peek().value in ("raw", "decorrelated", "optimized", "physical"):
            stage = self.next().value
            if self.peek().kind == "IDENT" and self.peek().value == "plan":
                self.next()
            self.eat_kw("for")
        return ast.Explain(stage, self.parse_statement())

    def parse_show(self):
        self.expect_kw("show")
        if self.eat_kw("all"):
            return ast.Show("all")
        what = self.ident()
        on = None
        if self.eat_kw("from") or self.eat_kw("on"):
            on = self.ident()
        return ast.Show(what, on)

    def parse_drop(self):
        self.expect_kw("drop")
        if self.eat_kw("materialized"):
            self.expect_kw("view")
            kind = "materialized view"
        else:
            kind = self.ident()
        if_exists = False
        if self.eat_kw("if"):
            self.ident()  # exists
            if_exists = True
        name = self.ident()
        return ast.DropObject(kind, name, if_exists)

    # -- queries ----------------------------------------------------------------
    def parse_query(self) -> ast.Query:
        ctes: list = []
        recursive = False
        if self.at_kw("with") and not self.at_kw("when"):
            self.next()
            if self.peek().value == "mutually":
                self.next()
                if self.peek().value != "recursive":
                    raise ParseError("expected RECURSIVE after MUTUALLY")
                self.next()
                recursive = True
            elif self.peek().value == "recursive":
                self.next()
                recursive = True
            while True:
                name = self.ident()
                cols = []
                if self.at_op("("):
                    self.next()
                    while not self.at_op(")"):
                        cname = self.ident()
                        ctyp = self.parse_type_name()
                        cols.append((cname, ctyp))
                        self.eat_op(",")
                    self.expect_op(")")
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append(ast.CteBinding(name, q, tuple(cols)))
                if not self.eat_op(","):
                    break
        body = self.parse_set_expr()
        order_by = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by = self.parse_order_items()
        limit = None
        offset = 0
        if self.eat_kw("limit"):
            limit = int(self.next().value)
        if self.eat_kw("offset"):
            offset = int(self.next().value)
        return ast.Query(
            body, tuple(order_by), limit, offset, tuple(ctes), recursive
        )

    def parse_order_items(self) -> list:
        """Comma list of `expr [ASC|DESC] [NULLS FIRST|LAST]` items."""
        out = []
        while True:
            e = self.parse_expr()
            desc = False
            if self.eat_kw("desc"):
                desc = True
            elif self.eat_kw("asc"):
                pass
            nulls_last = None
            if self.eat_kw("nulls"):
                pos = self.ident().lower()
                if pos not in ("first", "last"):
                    raise ParseError(f"expected FIRST or LAST after NULLS, got {pos}")
                nulls_last = pos == "last"
            out.append(ast.OrderByItem(e, desc, nulls_last))
            if not self.eat_op(","):
                break
        return out

    def parse_over(self):
        """`OVER ( [PARTITION BY exprs] [ORDER BY items] )` if present, else None."""
        if not self.eat_kw("over"):
            return None
        self.expect_op("(")
        partition_by = []
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.parse_expr())
            while self.eat_op(","):
                partition_by.append(self.parse_expr())
        order_by = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by = self.parse_order_items()
        self.expect_op(")")
        return ast.WindowSpec(tuple(partition_by), tuple(order_by))

    def parse_set_expr(self):
        left = self.parse_select_core()
        while self.at_kw("union", "except", "intersect"):
            op = self.next().value
            if self.eat_kw("all"):
                op += "_all"
            elif self.eat_kw("distinct"):
                pass
            right = self.parse_select_core()
            left = ast.SetOp(op, left, right)
        return left

    def parse_select_core(self):
        if self.eat_op("("):
            q = self.parse_set_expr()
            self.expect_op(")")
            return q
        if self.at_kw("values"):
            return self.parse_values()
        self.expect_kw("select")
        distinct = False
        if self.eat_kw("distinct"):
            distinct = True
        elif self.eat_kw("all"):
            pass
        items = []
        while True:
            if self.at_op("*"):
                self.next()
                items.append(ast.SelectItem(ast.Star()))
            elif (
                self.peek().kind in ("IDENT",)
                and self.peek(1).kind == "OP"
                and self.peek(1).value == "."
                and self.peek(2).kind == "OP"
                and self.peek(2).value == "*"
            ):
                q = self.ident()
                self.next()
                self.next()
                items.append(ast.SelectItem(ast.Star(qualifier=q)))
            else:
                e = self.parse_expr()
                alias = None
                if self.eat_kw("as"):
                    alias = self.ident()
                elif self.peek().kind == "IDENT":
                    alias = self.ident()
                items.append(ast.SelectItem(e, alias))
            if not self.eat_op(","):
                break
        from_ = ()
        if self.eat_kw("from"):
            rels = [self.parse_table_factor_with_joins()]
            while self.eat_op(","):
                rels.append(self.parse_table_factor_with_joins())
            from_ = tuple(rels)
        where = self.parse_expr() if self.eat_kw("where") else None
        group_by: tuple = ()
        if self.eat_kw("group"):
            self.expect_kw("by")
            gb = [self.parse_expr()]
            while self.eat_op(","):
                gb.append(self.parse_expr())
            group_by = tuple(gb)
        having = self.parse_expr() if self.eat_kw("having") else None
        return ast.Select(tuple(items), from_, where, group_by, having, distinct)

    def parse_values(self):
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = []
            while not self.at_op(")"):
                row.append(self.parse_expr())
                self.eat_op(",")
            self.expect_op(")")
            rows.append(tuple(row))
            if not self.eat_op(","):
                break
        return ast.Values(tuple(rows))

    def parse_table_factor_with_joins(self):
        left = self.parse_table_factor()
        while True:
            kind = None
            if self.eat_kw("cross"):
                self.expect_kw("join")
                kind = "cross"
            elif self.at_kw("join"):
                self.next()
                kind = "inner"
            elif self.at_kw("inner") and self.peek(1).value == "join":
                self.next(); self.next()
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.next().value
                self.eat_kw("outer")
                self.expect_kw("join")
            else:
                break
            right = self.parse_table_factor()
            on = None
            if kind != "cross":
                self.expect_kw("on")
                on = self.parse_expr()
            left = ast.JoinClause(left, right, kind, on)
        return left

    def parse_table_factor(self):
        if self.eat_op("("):
            q = self.parse_query()
            self.expect_op(")")
            self.eat_kw("as")
            alias = self.ident()
            return ast.SubqueryRef(q, alias)
        if self.peek().kind == "IDENT" and self.peek(1).kind == "OP" and self.peek(1).value == "(":
            fname = self.ident()
            self.next()
            args = []
            if not self.at_op(")"):
                args.append(self.parse_expr())
                while self.eat_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            alias = None
            if self.eat_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "IDENT":
                alias = self.ident()
            return ast.TableFuncRef(fname, tuple(args), alias)
        name = self.ident()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "IDENT":
            alias = self.ident()
        return ast.TableRef(name, alias)

    # -- expressions (precedence climbing) ---------------------------------------
    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_and()
        while self.eat_kw("or"):
            left = ast.BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.eat_kw("and"):
            left = ast.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.eat_kw("not"):
            return ast.UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self):
        left = self.parse_is_between_in()
        t = self.peek()
        if t.kind == "OP" and t.value in ("=", "<", ">", "<=", ">=", "<>", "!="):
            self.next()
            op = {"!=": "<>"}.get(t.value, t.value)
            return ast.BinaryOp(op, left, self.parse_is_between_in())
        if self.at_kw("like", "ilike"):
            op = self.next().value
            return ast.BinaryOp(op, left, self.parse_is_between_in())
        if self.at_kw("not") and self.peek(1).value in ("like", "ilike"):
            self.next()
            op = "not_" + self.next().value
            return ast.BinaryOp(op, left, self.parse_is_between_in())
        return left

    def parse_is_between_in(self):
        left = self.parse_additive()
        while True:
            if self.eat_kw("is"):
                negated = self.eat_kw("not")
                self.expect_kw("null")
                left = ast.IsNull(left, negated)
            elif self.at_kw("between") or (
                self.at_kw("not") and self.peek(1).value == "between"
            ):
                negated = self.eat_kw("not")
                self.expect_kw("between")
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = ast.Between(left, low, high, negated)
            elif self.at_kw("in") or (self.at_kw("not") and self.peek(1).value == "in"):
                negated = self.eat_kw("not")
                self.expect_kw("in")
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InList(left, (ast.Subquery(q),), negated)
                else:
                    items = [self.parse_expr()]
                    while self.eat_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(items), negated)
            else:
                return left

    def parse_additive(self):
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("+", "-", "||"):
                self.next()
                left = ast.BinaryOp(t.value, left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self):
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("*", "/", "%"):
                self.next()
                left = ast.BinaryOp(t.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self):
        if self.at_op("-"):
            self.next()
            return ast.UnaryOp("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        return self.parse_cast_suffix()

    def parse_cast_suffix(self):
        e = self.parse_primary()
        while True:
            if self.at_op("::"):
                self.next()
                e = ast.Cast(e, self.parse_type_name())
            elif self.at_op("->") or self.at_op("->>"):
                op = self.next().value
                e = ast.BinaryOp(op, e, self.parse_primary())
            else:
                return e

    def parse_case(self):
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            res = self.parse_expr()
            whens.append((cond, res))
        else_ = None
        if self.eat_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return ast.Case(operand, tuple(whens), else_)

    def parse_primary(self):
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return ast.NumberLit(t.value)
        if t.kind == "STRING":
            self.next()
            return ast.StringLit(t.value)
        if self.at_kw("true"):
            self.next()
            return ast.BoolLit(True)
        if self.at_kw("false"):
            self.next()
            return ast.BoolLit(False)
        if self.at_kw("null"):
            self.next()
            return ast.NullLit()
        if self.at_kw("date"):
            self.next()
            lit = self.next()
            return ast.DateLit(lit.value)
        if self.at_kw("interval"):
            self.next()
            lit = self.next()
            if lit.kind != "STRING":
                raise ParseError("INTERVAL requires a quoted string")
            return ast.IntervalLit(lit.value)
        if self.at_kw("cast"):
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            typ = self.parse_type_name()
            self.expect_op(")")
            return ast.Cast(e, typ)
        if self.at_kw("case"):
            return self.parse_case()
        if self.peek().kind == "IDENT" and self.peek().value == "extract" and self.peek(1).value == "(":
            self.next()
            self.next()
            fld = self.ident()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return ast.FuncCall(f"extract_{fld}", (e,))
        if self.at_kw("when"):
            # only reachable from parse_case's operand-less form
            raise ParseError("WHEN outside CASE")
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ast.Subquery(q, exists=True)
        if self.at_op("("):
            self.next()
            if self.at_kw("select"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.Subquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "PARAM":
            self.next()
            return ast.Param(int(t.value))
        if t.kind in ("IDENT", "KW"):
            name = self.ident()
            if self.at_op("("):  # function call
                self.next()
                distinct = self.eat_kw("distinct")
                if self.at_op("*"):
                    self.next()
                    self.expect_op(")")
                    return ast.FuncCall(
                        name, (), is_star=True, over=self.parse_over()
                    )
                args = []
                if not self.at_op(")"):
                    args.append(self.parse_expr())
                    while self.eat_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                return ast.FuncCall(name, tuple(args), distinct, over=self.parse_over())
            if self.at_op(".") and self.peek(1).kind in ("IDENT", "KW"):
                self.next()
                col = self.ident()
                return ast.Ident(col, qualifier=name)
            return ast.Ident(name)
        raise ParseError(f"unexpected token {t.value!r} in expression")


def parse_statements(sql: str) -> list:
    """Parse a ;-separated script."""
    out = []
    p = Parser(sql)
    while p.peek().kind != "EOF":
        out.append(p.parse_statement())
        while p.eat_op(";"):
            pass
    return out


def parse_statement(sql: str):
    stmts = parse_statements(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected exactly one statement, got {len(stmts)}")
    return stmts[0]
