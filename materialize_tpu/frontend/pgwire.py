"""pgwire — PostgreSQL wire protocol (v3) frontend.

The analogue of the reference's `mz-pgwire` (src/pgwire/src/server.rs:82
handle_connection, protocol.rs:145 run): startup handshake (SSLRequest
politely declined, cleartext), simple-query protocol with text-format
results, per-statement CommandComplete tags, ErrorResponse + ReadyForQuery
recovery, COPY TO STDOUT, and the extended query protocol
(Parse/Bind/Describe/Execute/Close/Sync with text parameters).

Every real postgres client (psql, psycopg, JDBC) speaking simple queries can
talk to this.
"""

from __future__ import annotations

import itertools
import secrets
import socket
import struct
import threading
import time

from ..adapter import Coordinator, ExecResult
from ..errors import IdleTimeout, TooManyConnections, sqlstate_of

_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102

# backend pids are process-global: two listeners sharing one coordinator
# must never hand out colliding (pid, secret) cancel identities
_PIDS = itertools.count(1)
_GSSENC_REQUEST = 80877104
_PROTO_V3 = 196608

# pg type OIDs (reference: mz-pgrepr oid mapping)
_OID_BOOL = 16
_OID_INT8 = 20
_OID_TEXT = 25
_OID_FLOAT8 = 701
_OID_NUMERIC = 1700


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _scan_params(sql: str) -> list[tuple[int, int, int]]:
    """Positions of $n placeholders OUTSIDE single-quoted strings.

    Returns [(start, end, param_index)] in order of appearance.
    """
    out = []
    i, n = 0, len(sql)
    in_str = False
    while i < n:
        c = sql[i]
        if in_str:
            if c == "'":
                if i + 1 < n and sql[i + 1] == "'":
                    i += 2
                    continue
                in_str = False
            i += 1
            continue
        if c == "'":
            in_str = True
            i += 1
            continue
        if c == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            out.append((i, j, int(sql[i + 1 : j])))
            i = j
            continue
        i += 1
    return out


def _has_bare_semicolon(sql: str) -> bool:
    """';' outside string literals and not merely trailing."""
    body = sql.strip().rstrip(";")
    i, n = 0, len(body)
    in_str = False
    while i < n:
        c = body[i]
        if in_str:
            if c == "'":
                if i + 1 < n and body[i + 1] == "'":
                    i += 2
                    continue
                in_str = False
        elif c == "'":
            in_str = True
        elif c == ";":
            return True
        i += 1
    return False


class PgConnection:
    """Per-connection pgwire protocol state machine.

    Transport-agnostic by construction: every outbound byte goes through
    `_send` (sendall on the owned socket), and the two blocking entry points
    — `run()`'s read loop and `_stream_subscription`'s inline drain — are
    only used by the threaded backend. The serve/ reactor drives the SAME
    state machine by feeding `_startup_packet`/`dispatch` with frames it
    framed itself and giving `sock` a buffering shim, so the bytes any
    client sees are identical across backends by construction.
    """

    def __init__(self, sock, coordinator: Coordinator, lock,
                 server=None):
        self.sock = sock
        self.coord = coordinator
        self.lock = lock
        self.server = server
        # threaded mode streams SUBSCRIBE inline (blocking drain); the
        # reactor flips this off and pumps `pending_stream` from the ring
        self.stream_inline = True
        self.pending_stream: dict | None = None
        self.session = coordinator.new_session()
        # cancellation identity (BackendKeyData): a CancelRequest must quote
        # this exact (pid, secret) pair; anything else is a silent no-op
        self.pid = next(_PIDS)
        self.secret = secrets.randbits(32)
        coordinator.cancel_keys[self.pid] = (self.secret, self.session)
        # extended query protocol state (protocol.rs StateMachine analogue)
        self.statements: dict[str, str] = {}  # name -> sql with $n params
        self.portals: dict[str, tuple] = {}  # name -> (sql, bound param values)
        # after an error, skip messages until Sync (spec-mandated)
        self.in_error = False

    def _admitted(self, sql: str):
        """Shared admission discipline (adapter/overload.py `admitted`):
        statement gate → peek gate for peek-shaped scripts → lock."""
        from ..adapter.overload import admitted

        return admitted(self.coord, sql, self.lock)

    # -- startup ---------------------------------------------------------------
    def run(self) -> None:
        try:
            # startup budget: a dialed-but-silent connection counts against
            # max_connections from accept, so it may not camp in the startup
            # read forever — 30 s to produce a startup packet or the slot is
            # reclaimed (socket.timeout lands in the outer handler below)
            self.sock.settimeout(30.0)
            if not self._startup():
                return
            self._send_ready()
            while True:
                # idle-session budget: a connection holding no statement may
                # not camp forever (57P05). The timeout covers only the wait
                # for a message's FIRST byte — a slow link mid-message or a
                # slow reader mid-result is not idle. socket.timeout must be
                # caught HERE — the outer OSError handler would mask it.
                idle_ms = int(
                    self.session.get("idle_in_transaction_session_timeout")
                )
                try:
                    tag, payload = self._read_message(
                        first_byte_timeout=idle_ms / 1000.0 if idle_ms > 0 else None
                    )
                except socket.timeout:
                    self._send_idle_timeout_error()
                    break
                if not self.dispatch(tag, payload):
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            self.coord.cancel_keys.pop(self.pid, None)
            if self.server is not None:
                self.server.conn_done()
            try:
                self.sock.close()
            except OSError:
                pass

    def dispatch(self, tag, payload) -> bool:
        """Process ONE framed protocol message; returns False when the
        connection should close (EOF or Terminate). Both backends call this
        — the threaded run() loop above, the reactor per readable frame."""
        if tag is None or tag == b"X":
            return False
        if tag == b"Q":
            sql = payload[:-1].decode()
            self._simple_query(sql)
        elif tag == b"S":  # Sync: clear error state, drop portals
            self.in_error = False
            self.portals.clear()
            self._send_ready()
        elif tag == b"H":  # Flush
            pass
        elif tag in (b"P", b"B", b"D", b"E", b"C"):
            if self.in_error:
                return True  # discard until Sync, per spec
            try:
                handler = {
                    b"P": self._handle_parse,
                    b"B": self._handle_bind,
                    b"D": self._handle_describe,
                    b"E": self._handle_execute,
                    b"C": self._handle_close,
                }[tag]
                handler(payload)
            except (ConnectionError, OSError):
                raise
            except Exception as e:  # malformed payloads etc.
                self._ext_error("08P01", f"protocol error: {e}")
        else:
            self._send_error("08P01", f"unexpected message {tag!r}")
            self._send_ready()
        return True

    def _send_idle_timeout_error(self) -> None:
        self.coord.overload.bump("idle_timeouts")
        err = IdleTimeout(
            "terminating connection due to "
            "idle-in-transaction session timeout"
        )
        self._send_error(err.sqlstate, str(err))

    def _saturated(self) -> bool:
        """max_connections admission: this connection counts itself."""
        limit = int(self.coord.configs.get("max_connections"))
        return (
            limit > 0
            and self.server is not None
            and self.server.active_connections > limit
        )

    def _handle_cancel_request(self, body: bytes) -> None:
        """CancelRequest: out-of-band, lock-free, secret-gated. The wrong
        secret is a silent no-op (per spec: no response either way) — the
        requester learns nothing about live pids."""
        if len(body) < 12:
            return
        pid, secret = struct.unpack(">II", body[4:12])
        entry = self.coord.cancel_keys.get(pid)
        if entry is not None and entry[0] == secret:
            entry[1].cancelled.set()
            self.coord.overload.bump("cancel_requests")
        else:
            self.coord.overload.bump("cancel_requests_ignored")

    def _startup(self) -> bool:
        while True:
            head = self._read_exact(4)
            if head is None:
                return False
            (n,) = struct.unpack(">I", head)
            body = self._read_exact(n - 4)
            if body is None:
                return False
            verdict = self._startup_packet(body)
            if verdict == "more":
                continue
            return verdict == "ready"

    def _startup_packet(self, body: bytes) -> str:
        """One length-prefixed startup-phase packet: 'more' (SSL/GSS probe
        answered, keep reading), 'ready' (handshake complete), or 'close'."""
        (code,) = struct.unpack(">I", body[:4])
        if code == _CANCEL_REQUEST:
            # processed even at max_connections: a saturated server that
            # refuses cancels could never be relieved by its own clients
            self._handle_cancel_request(body)
            return "close"
        if self._saturated():
            # shed at the first request/response exchange, so the
            # balancer's round-trip probe (SSLRequest → expects 'N')
            # sees saturation, not health; retryable by contract
            self.coord.overload.bump("connections_rejected")
            err = TooManyConnections("too many connections; retry later")
            self._send_error(err.sqlstate, str(err))
            return "close"
        if code in (_SSL_REQUEST, _GSSENC_REQUEST):
            self._send(b"N")  # no TLS; client retries cleartext
            return "more"
        if code != _PROTO_V3:
            self._send_error("08P01", f"unsupported protocol {code}")
            return "close"
        self._parse_startup_params(body[4:])
        self._send_startup_ok()
        return "ready"

    def _parse_startup_params(self, body: bytes) -> None:
        """key\\0value\\0…\\0: the `user` parameter becomes the session's
        tenant identity (max_subscriptions_per_user budgets)."""
        parts = body.split(b"\x00")
        for i in range(0, len(parts) - 1, 2):
            if parts[i] == b"user" and parts[i + 1]:
                self.session.user = parts[i + 1].decode(errors="replace")

    def _send_startup_ok(self) -> None:
        self._send(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "9.5.0 materialize_tpu"),
            ("client_encoding", "UTF8"),
            ("DateStyle", "ISO"),
            ("integer_datetimes", "on"),
            ("standard_conforming_strings", "on"),
        ):
            self._send(_msg(b"S", _cstr(k) + _cstr(v)))
        # BackendKeyData: the (pid, secret) a client must echo to cancel
        self._send(_msg(b"K", struct.pack(">II", self.pid, self.secret)))

    # -- messages --------------------------------------------------------------
    def _send(self, data: bytes) -> None:
        """Single egress seam: the threaded backend writes through to the
        socket; the reactor's sock shim buffers into the connection's
        outbuf instead."""
        self.sock.sendall(data)

    def _read_exact(self, n: int):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_message(self, first_byte_timeout: float | None = None):
        # the idle window applies only to the gap BETWEEN messages: once the
        # tag byte arrives, the rest of the message reads untimed
        self.sock.settimeout(first_byte_timeout)
        try:
            tag = self._read_exact(1)
        finally:
            self.sock.settimeout(None)
        if tag is None:
            return None, None
        head = self._read_exact(4)
        if head is None:
            return None, None
        (n,) = struct.unpack(">I", head)
        payload = self._read_exact(n - 4) if n > 4 else b""
        return tag, payload

    def _send_ready(self) -> None:
        self._send(_msg(b"Z", b"I"))

    def _send_error(self, code: str, message: str) -> None:
        fields = b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
        self._send(_msg(b"E", fields))

    # -- queries ---------------------------------------------------------------
    def _simple_query(self, sql: str) -> None:
        if not sql.strip():
            self._send(_msg(b"I", b""))  # EmptyQueryResponse
            self._send_ready()
            return
        # a cancel targets THIS query message (which may be a whole script):
        # one left set by a race after the previous message is dropped now,
        # pg-style; one landing any time during this script kills it (57014)
        self.session.cancelled.clear()
        # statement_timeout windows open at receipt: queue wait counts
        self.session.arrival = time.monotonic()
        try:
            with self._admitted(sql):
                results = self.coord.execute_script(sql, self.session)
        except Exception as e:
            self._send_error(sqlstate_of(e), str(e))
            self._send_ready()
            return
        self._send_results(results, with_description=True)
        if self.pending_stream is not None:
            # reactor mode: the stream pump owns the connection now; the
            # ReadyForQuery rides behind the stream's terminal messages
            self.pending_stream["send_ready"] = True
        else:
            self._send_ready()

    def _send_results(self, results, with_description: bool) -> None:
        results = list(results)
        for i, r in enumerate(results):
            if r.kind == "rows":
                if with_description:
                    self._send_row_description(r)
                for row in r.rows:
                    self._send_data_row(row)
                self._send(_msg(b"C", _cstr(f"SELECT {len(r.rows)}")))
            elif r.kind == "subscribe":
                if self.stream_inline:
                    self._stream_subscription(r)
                else:
                    # reactor mode: emit the COPY header and hand the pump
                    # the subscription + whatever results trail it (they go
                    # out after the stream ends, as the inline path orders)
                    self._send_copy_header(len(r.subscription.columns))
                    self.pending_stream = {
                        "sub": r.subscription,
                        "rest": results[i + 1:],
                        "with_description": with_description,
                        "send_ready": False,
                    }
                    return
            elif r.kind == "copy":
                # CopyOutResponse (text format), CopyData lines, CopyDone
                ncols = len(r.columns)
                self._send(
                    _msg(b"H", b"\x00" + struct.pack(">H", ncols) + b"\x00\x00" * ncols)
                )
                data = getattr(r, "copy_data", "")
                if data:
                    self._send(_msg(b"d", data.encode()))
                self._send(_msg(b"c", b""))
                self._send(_msg(b"C", _cstr(r.status)))
            else:
                self._send(_msg(b"C", _cstr(r.status)))

    # -- SUBSCRIBE streaming -----------------------------------------------------
    def _stream_subscription(self, r: ExecResult) -> None:
        """SUBSCRIBE over COPY out (the reference's pgwire SUBSCRIBE shape,
        protocol.rs stream_rows): CopyOutResponse, then one CopyData text
        row `(mz_timestamp, mz_progressed, mz_diff, cols…)` per update,
        until the client cancels (57014), idles past
        idle_in_transaction_session_timeout with nothing delivered (57P05),
        falls behind the bounded queue (53400), sends any message (clean
        CopyDone), disconnects, or the collection is dropped. The queue is
        drained WITHOUT the coordinator lock — a slow client never stalls
        the command loop; only teardown takes it."""
        import select

        from ..errors import QueryCanceled, SqlError

        sub = r.subscription
        self._send_copy_header(len(sub.columns))
        idle_ms = int(self.session.get("idle_in_transaction_session_timeout"))
        last_activity = time.monotonic()
        delivered = 0
        try:
            while True:
                if self.session.cancelled.is_set():
                    raise QueryCanceled("canceling statement due to user request")
                # client traffic ends the stream: CopyDone/CopyFail/anything
                # means "stop subscribing" (run() processes the pending
                # message after CommandComplete); EOF means the client is gone
                ready, _w, _x = select.select([self.sock], [], [], 0)
                if ready:
                    try:
                        peeked = self.sock.recv(1, socket.MSG_PEEK)
                    except OSError:
                        peeked = b""
                    if peeked == b"":
                        self._teardown_sub(sub, "cancelled")
                        return  # connection dropped; run() sees EOF next read
                    break
                # one pre-encoded frame per tick from the shared fan-out
                # ring (egress/fanout.py): the bytes were rendered once per
                # (collection, tick), not per subscriber
                frame = sub.pop_frame("pgcopy", timeout=0.05)
                if frame is not None:
                    self._send(frame.data)
                    delivered += frame.count
                    last_activity = time.monotonic()
                    continue
                if sub.state != "active":
                    break  # dropped: the stream ends cleanly
                if idle_ms > 0 and (time.monotonic() - last_activity) > idle_ms / 1000.0:
                    self.coord.overload.bump("idle_timeouts")
                    raise IdleTimeout(
                        "terminating SUBSCRIBE due to idle-in-transaction "
                        "session timeout"
                    )
        except SqlError as e:
            # 57014 / 57P05 / 53400: teardown releases the read hold and the
            # hidden MV's trace holds; the error ends the COPY per protocol
            self._teardown_sub(sub, "cancelled")
            self._send_error(e.sqlstate, str(e))
            return
        self._teardown_sub(sub, "cancelled")
        self._send(_msg(b"c", b""))
        self._send(_msg(b"C", _cstr(f"SUBSCRIBE {delivered}")))

    def _teardown_sub(self, sub, state: str) -> None:
        with self.lock:
            self.coord.teardown_subscription(sub.sub_id, state=state)

    def _send_copy_header(self, data_columns: int) -> None:
        """CopyOutResponse for a SUBSCRIBE stream: text format, the three
        mz_* columns plus the collection's data columns. Row bytes are
        rendered by egress/fanout.py `encode_pgcopy` — one encode per
        (collection, tick), shared by every subscriber."""
        ncols = 3 + data_columns
        self._send(
            _msg(b"H", b"\x00" + struct.pack(">H", ncols) + b"\x00\x00" * ncols)
        )

    # -- extended query protocol ------------------------------------------------
    def _ext_error(self, code: str, message: str) -> None:
        """Error inside the extended flow: report and ignore until Sync."""
        self._send_error(code, message)
        self.in_error = True

    @staticmethod
    def _read_cstr(payload: bytes, off: int) -> tuple[str, int]:
        end = payload.index(b"\x00", off)
        return payload[off:end].decode(), end + 1

    def _handle_parse(self, payload: bytes) -> None:
        name, off = self._read_cstr(payload, 0)
        sql, off = self._read_cstr(payload, off)
        # declared parameter type OIDs are accepted and ignored (text mode)
        if name and name in self.statements:
            self._ext_error("42P05", f"prepared statement {name!r} already exists")
            return
        if _has_bare_semicolon(sql):
            self._ext_error("42601", "multiple statements not allowed in Parse")
            return
        self.statements[name] = sql
        self._send(_msg(b"1", b""))  # ParseComplete

    def _handle_bind(self, payload: bytes) -> None:
        portal, off = self._read_cstr(payload, 0)
        stmt, off = self._read_cstr(payload, off)
        (n_fmt,) = struct.unpack(">H", payload[off : off + 2])
        off += 2
        fmts = []
        for _ in range(n_fmt):
            (f,) = struct.unpack(">H", payload[off : off + 2])
            fmts.append(f)
            off += 2
        (n_params,) = struct.unpack(">H", payload[off : off + 2])
        off += 2
        params: list[str | None] = []
        for i in range(n_params):
            (ln,) = struct.unpack(">i", payload[off : off + 4])
            off += 4
            if ln < 0:
                params.append(None)
            else:
                fmt = fmts[i] if i < len(fmts) else (fmts[0] if len(fmts) == 1 else 0)
                if fmt != 0:
                    self._ext_error("0A000", "binary parameters not supported")
                    return
                params.append(payload[off : off + ln].decode())
                off += ln
        sql = self.statements.get(stmt)
        if sql is None:
            self._ext_error("26000", f"unknown prepared statement {stmt!r}")
            return
        # parameters stay structured values bound at plan time ($n is a
        # planner placeholder, ast.Param) — never spliced into SQL text
        for _s, _e, idx in _scan_params(sql):
            if not (1 <= idx <= len(params)):
                self._ext_error("08P01", f"parameter ${idx} not bound")
                return
        self.portals[portal] = (sql, tuple(params))
        self._send(_msg(b"2", b""))  # BindComplete

    def _describe_columns(self, sql: str, params=None):
        """Column (name, oid) pairs for a statement, or None for no result set."""
        from ..repr.types import ColType
        from ..sql import ast as _ast
        from ..sql.parser import parse_statement

        stmt = parse_statement(sql)
        if not isinstance(stmt, _ast.SelectStatement):
            return None
        with self.lock:
            self.coord.planner.set_params(params)
            try:
                pq = self.coord.planner.plan_query(stmt.query)
            finally:
                self.coord.planner.set_params(None)
        oid_of = {
            ColType.INT64: _OID_INT8,
            ColType.INT32: _OID_INT8,
            ColType.BOOL: _OID_BOOL,
            ColType.FLOAT64: _OID_FLOAT8,
            ColType.NUMERIC: _OID_NUMERIC,
        }
        return [
            (c.name or f"column{i+1}", oid_of.get(c.typ.col, _OID_TEXT))
            for i, c in enumerate(pq.scope.cols)
        ]

    def _send_description(self, cols) -> None:
        payload = struct.pack(">H", len(cols))
        for name, oid in cols:
            payload += _cstr(name) + struct.pack(">IhIhih", 0, 0, oid, -1, -1, 0)
        self._send(_msg(b"T", payload))

    def _handle_describe(self, payload: bytes) -> None:
        kind = payload[0:1]
        name, _ = self._read_cstr(payload, 1)
        if kind == b"S":
            sql = self.statements.get(name)
            if sql is None:
                self._ext_error("26000", f"unknown prepared statement {name!r}")
                return
            n_params = len({idx for _s, _e, idx in _scan_params(sql)})
            self._send(
                _msg(b"t", struct.pack(">H", n_params) + struct.pack(">I", _OID_TEXT) * n_params)
            )
            params = None
        else:
            entry = self.portals.get(name)
            if entry is None:
                self._ext_error("34000", f"unknown portal {name!r}")
                return
            sql, params = entry
        # best-effort planning: statements may still contain unbound $n
        try:
            cols = self._describe_columns(sql, params)
        except Exception:
            cols = None
        if cols:
            self._send_description(cols)
        else:
            self._send(_msg(b"n", b""))  # NoData

    def _handle_execute(self, payload: bytes) -> None:
        portal, off = self._read_cstr(payload, 0)
        entry = self.portals.get(portal)
        if entry is None:
            self._ext_error("34000", f"unknown portal {portal!r}")
            return
        sql, params = entry
        self.session.cancelled.clear()  # per Execute message, like _simple_query
        self.session.arrival = time.monotonic()
        try:
            with self._admitted(sql):
                results = self.coord.execute_script(sql, self.session, params=params)
        except Exception as e:
            self._ext_error(sqlstate_of(e), str(e))
            return
        # per protocol, Execute emits DataRows only (RowDescription belongs
        # to Describe)
        self._send_results(results, with_description=False)

    def _handle_close(self, payload: bytes) -> None:
        kind = payload[0:1]
        name, _ = self._read_cstr(payload, 1)
        if kind == b"S":
            self.statements.pop(name, None)
        else:
            self.portals.pop(name, None)
        self._send(_msg(b"3", b""))  # CloseComplete

    def _send_row_description(self, r: ExecResult) -> None:
        payload = struct.pack(">H", len(r.columns))
        for i, name in enumerate(r.columns):
            oid = _OID_TEXT
            if r.rows:
                v = r.rows[0][i]
                if isinstance(v, bool):
                    oid = _OID_BOOL
                elif isinstance(v, int):
                    oid = _OID_INT8
                elif isinstance(v, float):
                    oid = _OID_FLOAT8
            payload += (
                _cstr(name or f"column{i+1}")
                + struct.pack(">IhIhih", 0, 0, oid, -1, -1, 0)
            )
        self._send(_msg(b"T", payload))

    def _send_data_row(self, row: tuple) -> None:
        payload = struct.pack(">H", len(row))
        for v in row:
            if v is None:
                payload += struct.pack(">i", -1)
                continue
            if isinstance(v, bool):
                text = "t" if v else "f"
            else:
                text = str(v)
            data = text.encode()
            payload += struct.pack(">I", len(data)) + data
        self._send(_msg(b"D", payload))


class PgServer:
    """pgwire listener: thread-per-connection behind connection admission.

    Listener hygiene (ROADMAP known facts: this sandbox's `accept()` is NOT
    interrupted by closing the listener): the server socket carries a
    timeout, so the accept loop wakes periodically, observes the stop flag,
    and exits — `close()` always terminates the thread. Connection counting
    lives here; the per-connection max_connections shed happens inside
    `PgConnection._startup` so CancelRequests still get through at the limit.
    """

    def __init__(self, coordinator: Coordinator, host: str, port: int,
                 lock: threading.Lock):
        self.coord = coordinator
        self.lock = lock
        self.stop = threading.Event()
        self._count_lock = threading.Lock()
        self.active_connections = 0
        self.srv = socket.create_server((host, port))
        self.srv.listen(64)
        self.srv.settimeout(0.5)
        self.thread = threading.Thread(target=self._accept_loop, daemon=True)
        self.thread.start()

    # socket-compatible surface (tests and callers hold the old return shape)
    def getsockname(self):
        return self.srv.getsockname()

    def close(self) -> None:
        self.stop.set()
        try:
            self.srv.close()
        except OSError:
            pass

    def conn_done(self) -> None:
        with self._count_lock:
            self.active_connections -= 1

    def _accept_loop(self) -> None:
        while not self.stop.is_set():
            try:
                conn, _addr = self.srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._count_lock:
                self.active_connections += 1
            c = None
            try:
                c = PgConnection(conn, self.coord, self.lock, server=self)
                threading.Thread(target=c.run, daemon=True).start()
            except Exception:
                # e.g. OS thread exhaustion under a connection storm: drop
                # THIS connection, never the listener — an accept-loop death
                # here would turn overload into a permanent outage
                with self._count_lock:
                    self.active_connections -= 1
                if c is not None:
                    self.coord.cancel_keys.pop(c.pid, None)
                try:
                    conn.close()
                except OSError:
                    pass


def resolve_frontend_backend(coordinator, backend: str | None = None) -> str:
    """'thread' or 'reactor' from an explicit override or the
    `frontend_backend` dyncfg ('auto' picks the reactor — the serving plane
    built for fan-out; 'thread' keeps the historical accept loops for
    bisection)."""
    mode = backend or str(coordinator.configs.get("frontend_backend"))
    if mode == "auto":
        mode = "reactor"
    if mode not in ("thread", "reactor"):
        raise ValueError(f"unknown frontend_backend {mode!r}")
    return mode


def serve_pgwire(
    coordinator: Coordinator,
    host: str = "127.0.0.1",
    port: int = 6877,
    lock: threading.Lock | None = None,
    backend: str | None = None,
    reactor=None,
):
    """Start the pgwire listener; returns (server, accept thread). The
    server exposes getsockname()/close() like the raw socket it used to be.
    The serving plane is picked by `backend` / the frontend_backend dyncfg;
    pass `reactor` to share one event loop across frontends."""
    lock = lock or threading.Lock()
    if resolve_frontend_backend(coordinator, backend) == "reactor":
        from ..serve import serve_pgwire_reactor

        server = serve_pgwire_reactor(
            coordinator, host, port, lock, reactor=reactor
        )
        return server, server.thread
    server = PgServer(coordinator, host, port, lock)
    return server, server.thread
