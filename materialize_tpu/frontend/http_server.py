"""HTTP SQL frontend — the environmentd HTTP API analogue.

The reference serves SQL over HTTP/WS next to pgwire
(src/environmentd/src/http). This server exposes:

  POST /api/sql          {"query": "stmt; stmt; …"}  → {"results": […]}
  POST /api/promote      finish a 0dt handoff (preflight → leader)
  POST /api/subscribe    {"query": "SELECT …"}        → {"subscription_id": …}
  GET  /api/subscribe/<id>/poll                       → {"updates": […], "frontier": N}
  GET  /api/readyz                                    → "ok"
  GET  /metrics                                       → Prometheus text format

Commands are serialized through a lock, preserving the reference's
single-threaded coordinator command loop semantics (coord.rs:3822).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..adapter import Coordinator


def _json_default(v):
    import numpy as np

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    raise TypeError(f"not serializable: {type(v)}")


class SqlHandler(BaseHTTPRequestHandler):
    coordinator: Coordinator = None
    lock: threading.Lock = None

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code: int, body, content_type="application/json"):
        data = (
            body.encode()
            if isinstance(body, str)
            else json.dumps(body, default=_json_default).encode()
        )
        self.send_response(code)
        self.send_header("content-type", content_type)
        self.send_header("content-length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_body(self) -> dict:
        n = int(self.headers.get("content-length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw)

    def do_GET(self):
        if self.path == "/api/readyz":
            return self._reply(200, "ok", "text/plain")
        if self.path == "/metrics":
            return self._reply(200, self._metrics_text(), "text/plain")
        if self.path.startswith("/prof/cpu"):
            from urllib.parse import parse_qs, urlparse

            from ..utils.prof import cpu_profile_folded

            seconds = 1.0
            qs = parse_qs(urlparse(self.path).query)
            if "seconds" in qs:
                try:
                    seconds = min(float(qs["seconds"][0]), 30.0)
                except ValueError:
                    pass
            return self._reply(200, cpu_profile_folded(seconds), "text/plain")
        if self.path.startswith("/prof/heap"):
            from ..utils.prof import heap_profile_text

            return self._reply(200, heap_profile_text(), "text/plain")
        if self.path.startswith("/api/subscribe/") and self.path.endswith("/poll"):
            sub_id = self.path.split("/")[3]
            with self.lock:
                try:
                    rows, frontier = self.coordinator.poll_subscription(sub_id)
                except KeyError:
                    return self._reply(404, {"error": f"unknown subscription {sub_id}"})
            updates = [
                {"row": list(data), "timestamp": ts, "diff": d} for data, ts, d in rows
            ]
            return self._reply(200, {"updates": updates, "frontier": frontier})
        return self._reply(404, {"error": "not found"})

    def do_POST(self):
        if self.path == "/api/sql":
            from ..errors import AdmissionShed, sqlstate_of

            try:
                doc = self._read_body()
                sql = doc.get("query", "")
                # same admission discipline as pgwire — literally the same
                # implementation (adapter/overload.py `admitted`): the
                # coordinator's waiting line is bounded across EVERY
                # frontend; a shed returns 503 + retryable code instead of
                # queuing forever
                from ..adapter.overload import admitted

                with admitted(self.coordinator, sql, self.lock):
                    results = self.coordinator.execute_script(sql)
                out = []
                for r in results:
                    if r.kind == "rows":
                        out.append(
                            {
                                "rows": [list(row) for row in r.rows],
                                "col_names": list(r.columns),
                            }
                        )
                    elif r.kind == "copy":
                        out.append(
                            {"copy": getattr(r, "copy_data", ""), "ok": r.status}
                        )
                    else:
                        out.append({"ok": r.status})
                return self._reply(200, {"results": out})
            except Exception as e:
                code = 503 if isinstance(e, AdmissionShed) else 400
                return self._reply(
                    code, {"error": str(e), "code": sqlstate_of(e)}
                )
        if self.path == "/api/promote":
            try:
                with self.lock:
                    self.coordinator.promote()
                return self._reply(200, {"state": self.coordinator.deploy_state})
            except Exception as e:
                return self._reply(400, {"error": str(e)})
        if self.path == "/api/subscribe":
            try:
                doc = self._read_body()
                with self.lock:
                    r = self.coordinator.execute(doc.get("query", ""))
                return self._reply(200, {"subscription_id": r.status})
            except Exception as e:
                return self._reply(400, {"error": str(e)})
        return self._reply(404, {"error": "not found"})

    def _metrics_text(self) -> str:
        return metrics_text(self.coordinator, self.lock)


def metrics_text(coord, lock) -> str:
    """Prometheus text exposition of coordinator/dataflow metrics
    (reference: mz_ore::metrics registries, src/compute/src/metrics.rs).

    Scrape-time values are *gathered* under ``lock`` — a fast pass copying
    numbers out of engine structures — and the text is rendered outside it,
    so a slow scrape never stalls the coordinator command loop. Replica
    counters ride the cached StatsReports (introspection_interval_s), fetched
    before the lock is taken.
    """
    from ..obs.metrics import REGISTRY, Snapshot

    reports = coord.replica_stats() if hasattr(coord, "replica_stats") else []
    with lock:
        oracle_ts = coord.oracle.read_ts()
        n_items = len(coord.catalog.items)
        n_dataflows = len(coord.dataflows)
        overload = sorted(coord.overload.snapshot().items())
        tm = coord.trace_manager
        shared_traces = tm.trace_count()
        hit_rate = tm.import_hit_rate()
        sharing = sorted(tm.stats.items())
        depths = [
            ((("gate", "statement"),), coord.admission.depth),
            ((("gate", "peek"),), coord.peek_gate.depth),
        ]
        # over a dict() snapshot (pgwire may hold a DIFFERENT lock): a
        # concurrent _record_peek inserting a fresh bucket key mid-iteration
        # would fault the scrape
        peek_hist = sorted(dict(getattr(coord, "peek_histogram", {})).items())
        ops, arr_recs, arr_bytes = [], [], []
        for gid, df, _src in coord.dataflows:
            for _obj, op_i, typ, el, _inv in df.operator_info():
                ops.append(((("dataflow", gid), ("op", op_i), ("type", typ)), el))
            for _obj, op_i, aname, _nb, _cap, rec, b in df.arrangement_info():
                labels = (("dataflow", gid), ("op", op_i), ("arrangement", aname))
                arr_recs.append((labels, rec))
                arr_bytes.append((labels, b))
    extras = [
        Snapshot(
            "mzt_oracle_read_ts", "gauge", "timestamp oracle read frontier",
            [((), oracle_ts)],
        ),
        Snapshot(
            "mzt_catalog_items", "gauge", "catalog item count", [((), n_items)]
        ),
        Snapshot(
            "mzt_dataflows", "gauge", "installed dataflow count",
            [((), n_dataflows)],
        ),
        Snapshot(
            "mzt_overload_counter", "counter", "overload protection decisions",
            [((("name", k),), v) for k, v in overload],
        ),
        Snapshot(
            "mzt_shared_traces", "gauge", "traces in the shared trace manager",
            [((), shared_traces)],
        ),
        Snapshot(
            "mzt_trace_import_hit_rate", "gauge",
            "fraction of trace imports served from a shared arrangement",
            [((), f"{hit_rate:.6f}")],
        ),
        Snapshot(
            "mzt_trace_sharing_counter", "counter", "trace sharing events",
            [((("name", k),), v) for k, v in sharing],
        ),
        Snapshot(
            "mzt_admission_queue_depth", "gauge",
            "statements/peeks waiting at an admission gate", depths,
        ),
        Snapshot(
            "mzt_peek_duration_bucket", "counter",
            "peek latency histogram (cumulative, power-of-two ns buckets)",
            [((("le_ns", k),), v) for k, v in peek_hist],
        ),
        Snapshot(
            "mzt_operator_elapsed_ns", "counter",
            "cumulative wall time inside each operator", ops,
        ),
        Snapshot(
            "mzt_arrangement_records", "gauge",
            "records held per arrangement", arr_recs,
        ),
        Snapshot(
            "mzt_arrangement_bytes", "gauge",
            "owner-charged bytes per arrangement (shared traces charged once)",
            arr_bytes,
        ),
    ]
    # replica-process registry snapshots (mesh exchange, persist ops, …)
    # surface under the same family names with a `process` label; render()
    # emits HELP/TYPE once per name even when a family spans processes
    for replica, rep in reports:
        proc = (("process", f"{replica}/{rep.process}"),)
        for name, kind, help_, samples in rep.counters:
            extras.append(
                Snapshot(
                    name, kind, help_,
                    [(tuple(labels) + proc, v) for labels, v in samples],
                )
            )
    return REGISTRY.expose(extra=extras)


def serve(
    coordinator: Coordinator, host: str = "127.0.0.1", port: int = 6875
) -> ThreadingHTTPServer:
    """Start the HTTP frontend (returns the server; call serve_forever or
    shutdown from the caller)."""
    handler = type(
        "BoundSqlHandler",
        (SqlHandler,),
        {"coordinator": coordinator, "lock": threading.Lock()},
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    return httpd
