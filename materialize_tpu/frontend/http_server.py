"""HTTP SQL frontend — the environmentd HTTP API analogue.

The reference serves SQL over HTTP/WS next to pgwire
(src/environmentd/src/http). This server exposes:

  POST /api/sql          {"query": "stmt; stmt; …"}  → {"results": […]}
  POST /api/promote      finish a 0dt handoff (preflight → leader)
  POST /api/subscribe    {"query": "SELECT …"}        → {"subscription_id": …}
  GET  /api/subscribe/<id>/poll                       → {"updates": […], "frontier": N}
  GET  /api/subscribe/<id>/stream                     → chunked NDJSON updates
  GET  /api/readyz                                    → "ok"
  GET  /metrics                                       → Prometheus text format

Commands are serialized through a lock, preserving the reference's
single-threaded coordinator command loop semantics (coord.rs:3822).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..adapter import Coordinator


def _json_default(v):
    import numpy as np

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    raise TypeError(f"not serializable: {type(v)}")


class SqlHandler(BaseHTTPRequestHandler):
    coordinator: Coordinator = None
    lock: threading.Lock = None
    # 1.1 so the SUBSCRIBE stream can use chunked transfer-encoding; every
    # non-streaming reply carries content-length, so keep-alive stays sound
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code: int, body, content_type="application/json"):
        data = (
            body.encode()
            if isinstance(body, str)
            else json.dumps(body, default=_json_default).encode()
        )
        self.send_response(code)
        self.send_header("content-type", content_type)
        self.send_header("content-length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_body(self) -> dict:
        n = int(self.headers.get("content-length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw)

    def do_GET(self):
        if self.path.startswith("/api/subscribe/") and self.path.endswith("/stream"):
            return self._stream_subscription(self.path.split("/")[3])
        code, body, ctype = route(self.coordinator, self.lock, "GET", self.path, b"")
        return self._reply(code, body, ctype)

    def _stream_subscription(self, sub_id: str):
        """Push SUBSCRIBE over HTTP: chunked NDJSON, one object per update
        `{"mz_timestamp":…,"mz_progressed":…,"mz_diff":…,"row":[…]}`,
        streamed until the collection is dropped, the client disconnects,
        the subscription is shed (terminal line with code 53400), or the
        idle timeout reaps it (terminal line with code 57P05). One chunk
        per pre-encoded FRAME from the shared fan-out ring — the bytes are
        rendered once per (collection, tick), not per subscriber — and the
        drain happens WITHOUT the coordinator lock."""
        from ..errors import IdleTimeout, SqlError

        found = stream_prelude(self.coordinator, self.lock, sub_id)
        if found is None:
            return self._reply(404, {"error": f"unknown subscription {sub_id}"})
        sub, idle_ms = found
        self.send_response(200)
        self.send_header("content-type", "application/x-ndjson")
        self.send_header("transfer-encoding", "chunked")
        self.end_headers()

        def chunk(data: bytes) -> bool:
            try:
                self.wfile.write(http_chunk(data))
                self.wfile.flush()
                return True
            except OSError:
                return False

        last_delivery = time.monotonic()
        try:
            while True:
                try:
                    frame = sub.pop_frame("ndjson", timeout=0.25)
                except SqlError as e:
                    chunk(stream_error_line(e))
                    break
                if frame is None:
                    if sub.state != "active":
                        break  # dropped: end the stream cleanly
                    if (
                        idle_ms > 0
                        and (time.monotonic() - last_delivery) > idle_ms / 1000.0
                    ):
                        self.coordinator.overload.bump("idle_timeouts")
                        err = IdleTimeout(
                            "terminating SUBSCRIBE stream due to "
                            "idle-in-transaction session timeout"
                        )
                        chunk(stream_error_line(err))
                        break
                    continue
                last_delivery = time.monotonic()
                if not chunk(frame.data):
                    break  # client went away: tear down below
        finally:
            with self.lock:
                self.coordinator.teardown_subscription(sub_id)
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except OSError:
            pass
        self.close_connection = True

    def do_POST(self):
        n = int(self.headers.get("content-length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        code, body, ctype = route(self.coordinator, self.lock, "POST", self.path, raw)
        return self._reply(code, body, ctype)

    def _metrics_text(self) -> str:
        return metrics_text(self.coordinator, self.lock)


def route(coord, lock, method: str, path: str, raw: bytes):
    """One non-streaming request → `(status, body, content_type)`.

    Shared verbatim by BOTH serving backends — the ThreadingHTTPServer
    handler above and the serve/ reactor's connection pump — so route
    behavior (status codes, error envelopes, admission discipline) cannot
    drift between them. The two chunked-streaming endpoints are the only
    paths handled by the callers themselves."""
    if method == "GET":
        if path == "/api/readyz":
            return 200, "ok", "text/plain"
        if path == "/metrics":
            return 200, metrics_text(coord, lock), "text/plain"
        if path.startswith("/prof/cpu"):
            from urllib.parse import parse_qs, urlparse

            from ..utils.prof import cpu_profile_folded

            seconds = 1.0
            qs = parse_qs(urlparse(path).query)
            if "seconds" in qs:
                try:
                    seconds = min(float(qs["seconds"][0]), 30.0)
                except ValueError:
                    pass
            return 200, cpu_profile_folded(seconds), "text/plain"
        if path.startswith("/prof/heap"):
            from ..utils.prof import heap_profile_text

            return 200, heap_profile_text(), "text/plain"
        if path.startswith("/api/subscribe/") and path.endswith("/poll"):
            from ..errors import SqlError

            sub_id = path.split("/")[3]
            with lock:
                try:
                    rows, frontier = coord.poll_subscription(sub_id)
                except KeyError:
                    return (
                        404,
                        {"error": f"unknown subscription {sub_id}"},
                        "application/json",
                    )
                except SqlError as e:  # shed (53400): report once, tear down
                    coord.teardown_subscription(sub_id)
                    return (
                        400,
                        {"error": str(e), "code": e.sqlstate},
                        "application/json",
                    )
            updates = [
                {"row": list(data), "timestamp": ts, "diff": d}
                for data, ts, d in rows
            ]
            return (
                200,
                {"updates": updates, "frontier": frontier},
                "application/json",
            )
        return 404, {"error": "not found"}, "application/json"
    if path == "/api/sql":
        from ..errors import AdmissionShed, sqlstate_of

        try:
            doc = json.loads(raw or b"{}")
            sql = doc.get("query", "")
            # same admission discipline as pgwire — literally the same
            # implementation (adapter/overload.py `admitted`): the
            # coordinator's waiting line is bounded across EVERY
            # frontend; a shed returns 503 + retryable code instead of
            # queuing forever
            from ..adapter.overload import admitted

            with admitted(coord, sql, lock):
                results = coord.execute_script(sql)
            out = []
            for r in results:
                if r.kind == "rows":
                    out.append(
                        {
                            "rows": [list(row) for row in r.rows],
                            "col_names": list(r.columns),
                        }
                    )
                elif r.kind == "copy":
                    out.append(
                        {"copy": getattr(r, "copy_data", ""), "ok": r.status}
                    )
                else:
                    out.append({"ok": r.status})
            return 200, {"results": out}, "application/json"
        except Exception as e:
            code = 503 if isinstance(e, AdmissionShed) else 400
            return (
                code,
                {"error": str(e), "code": sqlstate_of(e)},
                "application/json",
            )
    if path == "/api/promote":
        try:
            with lock:
                coord.promote()
            return 200, {"state": coord.deploy_state}, "application/json"
        except Exception as e:
            return 400, {"error": str(e)}, "application/json"
    if path == "/api/subscribe":
        try:
            doc = json.loads(raw or b"{}")
            with lock:
                session = None
                if doc.get("user"):
                    # tenant identity for max_subscriptions_per_user budgets
                    # (pgwire clients carry it in the startup packet)
                    session = coord.new_session()
                    session.user = str(doc["user"])
                r = coord.execute(doc.get("query", ""), session)
            return 200, {"subscription_id": r.status}, "application/json"
        except Exception as e:
            from ..errors import sqlstate_of

            err = {"error": str(e), "code": sqlstate_of(e)}
            # retryable sheds (53300: max_subscriptions_per_user, admission)
            # get 503 like /api/sql, so generic clients back off and retry
            status = 503 if getattr(e, "retryable", False) else 400
            return status, err, "application/json"
    return 404, {"error": "not found"}, "application/json"


def stream_prelude(coord, lock, sub_id: str):
    """Look up a subscription + idle budget for a /stream request (both
    backends); None means 404."""
    with lock:
        sub = coord.subscriptions.get(sub_id)
        idle_ms = int(
            coord.configs.get("idle_in_transaction_session_timeout")
        )
    if sub is None:
        return None
    return sub, idle_ms


def teardown(coord, lock, sub_id: str) -> None:
    """Tear a subscription down under the command lock — the stream-end
    path of both serving backends (the reactor runs this on its executor
    pool; callbacks on the loop never take the lock)."""
    with lock:
        coord.teardown_subscription(sub_id)


def http_chunk(data: bytes) -> bytes:
    """One HTTP/1.1 transfer-encoding chunk. Both backends emit one chunk
    per frame, so the raw chunked stream (not merely the de-chunked body)
    is byte-identical between them."""
    return f"{len(data):x}\r\n".encode() + data + b"\r\n"


def stream_error_line(e) -> bytes:
    """Terminal NDJSON error line for a shed/idle/cancelled stream."""
    return (json.dumps({"error": str(e), "code": e.sqlstate}) + "\n").encode()


def metrics_text(coord, lock) -> str:
    """Prometheus text exposition of coordinator/dataflow metrics
    (reference: mz_ore::metrics registries, src/compute/src/metrics.rs).

    Scrape-time values are *gathered* under ``lock`` — a fast pass copying
    numbers out of engine structures — and the text is rendered outside it,
    so a slow scrape never stalls the coordinator command loop. Replica
    counters ride the cached StatsReports (introspection_interval_s), fetched
    before the lock is taken.
    """
    from ..obs.metrics import REGISTRY, Snapshot

    reports = coord.replica_stats() if hasattr(coord, "replica_stats") else []
    with lock:
        oracle_ts = coord.oracle.read_ts()
        n_items = len(coord.catalog.items)
        n_dataflows = len(coord.dataflows)
        overload = sorted(coord.overload.snapshot().items())
        tm = coord.trace_manager
        shared_traces = tm.trace_count()
        hit_rate = tm.import_hit_rate()
        sharing = sorted(tm.stats.items())
        depths = [
            ((("gate", "statement"),), coord.admission.depth),
            ((("gate", "peek"),), coord.peek_gate.depth),
        ]
        # over a dict() snapshot (pgwire may hold a DIFFERENT lock): a
        # concurrent _record_peek inserting a fresh bucket key mid-iteration
        # would fault the scrape
        peek_hist = sorted(dict(getattr(coord, "peek_histogram", {})).items())
        sub_depth, sub_delivered, sink_frontier, sink_updates = [], [], [], []
        for sid, sub in sorted(coord.subscriptions.items()):
            labels = (("subscription", sid), ("object", sub.object_name))
            sub_depth.append((labels, sub.queue_depth()))
            sub_delivered.append((labels, sub.delivered))
        for snk in coord.sinks.values():
            labels = (("sink", snk.name), ("from", snk.from_name))
            sink_frontier.append((labels, snk.frontier))
            sink_updates.append((labels, snk.emitted_updates))
        ops, arr_recs, arr_bytes = [], [], []
        for gid, df, _src in coord.dataflows:
            for _obj, op_i, typ, el, _inv in df.operator_info():
                ops.append(((("dataflow", gid), ("op", op_i), ("type", typ)), el))
            for _obj, op_i, aname, _nb, _cap, rec, b in df.arrangement_info():
                labels = (("dataflow", gid), ("op", op_i), ("arrangement", aname))
                arr_recs.append((labels, rec))
                arr_bytes.append((labels, b))
    extras = [
        Snapshot(
            "mzt_oracle_read_ts", "gauge", "timestamp oracle read frontier",
            [((), oracle_ts)],
        ),
        Snapshot(
            "mzt_catalog_items", "gauge", "catalog item count", [((), n_items)]
        ),
        Snapshot(
            "mzt_dataflows", "gauge", "installed dataflow count",
            [((), n_dataflows)],
        ),
        Snapshot(
            "mzt_overload_counter", "counter", "overload protection decisions",
            [((("name", k),), v) for k, v in overload],
        ),
        Snapshot(
            "mzt_shared_traces", "gauge", "traces in the shared trace manager",
            [((), shared_traces)],
        ),
        Snapshot(
            "mzt_trace_import_hit_rate", "gauge",
            "fraction of trace imports served from a shared arrangement",
            [((), f"{hit_rate:.6f}")],
        ),
        Snapshot(
            "mzt_trace_sharing_counter", "counter", "trace sharing events",
            [((("name", k),), v) for k, v in sharing],
        ),
        Snapshot(
            "mzt_admission_queue_depth", "gauge",
            "statements/peeks waiting at an admission gate", depths,
        ),
        Snapshot(
            "mzt_peek_duration_bucket", "counter",
            "peek latency histogram (cumulative, power-of-two ns buckets)",
            [((("le_ns", k),), v) for k, v in peek_hist],
        ),
        Snapshot(
            "mzt_operator_elapsed_ns", "counter",
            "cumulative wall time inside each operator", ops,
        ),
        Snapshot(
            "mzt_arrangement_records", "gauge",
            "records held per arrangement", arr_recs,
        ),
        Snapshot(
            "mzt_arrangement_bytes", "gauge",
            "owner-charged bytes per arrangement (shared traces charged once)",
            arr_bytes,
        ),
        Snapshot(
            "mzt_egress_subscription_queue_depth", "gauge",
            "updates waiting in each subscription's bounded queue", sub_depth,
        ),
        Snapshot(
            "mzt_egress_subscription_delivered", "counter",
            "updates handed to each subscription's consumer", sub_delivered,
        ),
        Snapshot(
            "mzt_egress_sink_progress_frontier", "gauge",
            "durable progress frontier of each file sink", sink_frontier,
        ),
        Snapshot(
            "mzt_egress_sink_emitted_updates", "counter",
            "changelog updates committed by each file sink", sink_updates,
        ),
    ]
    # replica-process registry snapshots (mesh exchange, persist ops, …)
    # surface under the same family names with a `process` label; render()
    # emits HELP/TYPE once per name even when a family spans processes
    for replica, rep in reports:
        proc = (("process", f"{replica}/{rep.process}"),)
        for name, kind, help_, samples in rep.counters:
            extras.append(
                Snapshot(
                    name, kind, help_,
                    [(tuple(labels) + proc, v) for labels, v in samples],
                )
            )
    return REGISTRY.expose(extra=extras)


def serve(
    coordinator: Coordinator,
    host: str = "127.0.0.1",
    port: int = 6875,
    lock: threading.Lock | None = None,
    backend: str | None = None,
    reactor=None,
):
    """Start the HTTP frontend (returns the server; call serve_forever or
    shutdown from the caller — both backends expose that surface, plus
    `server_address` and `RequestHandlerClass.lock`). The serving plane is
    picked by `backend` / the frontend_backend dyncfg; pass `reactor` to
    share one event loop with the pgwire frontend."""
    from .pgwire import resolve_frontend_backend

    lock = lock or threading.Lock()
    if resolve_frontend_backend(coordinator, backend) == "reactor":
        from ..serve import serve_http_reactor

        return serve_http_reactor(coordinator, host, port, lock, reactor=reactor)
    handler = type(
        "BoundSqlHandler",
        (SqlHandler,),
        {"coordinator": coordinator, "lock": lock},
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    return httpd
