from .http_server import serve

__all__ = ["serve"]
