"""balancerd — stateless ingress router.

The analogue of the reference's `mz-balancerd` (src/balancerd/src/lib.rs:9-12):
a connection-level TCP proxy that spreads pgwire/HTTP clients across backend
environments. No protocol awareness needed for the splice — it moves bytes
both ways and removes itself from the failure story (stateless, restartable).

Health, however, needs a REAL round-trip: this sandbox's loopback stack lets
`connect()` to a dead port succeed (failure only surfaces on first recv — see
doc/ROADMAP.md known facts), so a bare-connect check would happily route
clients into a black hole. Every candidate backend is probed with a
request/response exchange first (the `ShardedComputeController._reachable`
discipline); dead backends are skipped — saturated ones too under the
protocol-aware probes (pg_probe/http_probe) — and a fully-dark backend set
sheds the client instead of hanging it.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
import time


def recv_probe(addr, timeout: float = 0.1) -> bool:
    """Protocol-neutral liveness round-trip: dial, then demand the kernel
    prove a peer exists. A dead port here accepts the dial but EOFs/errors
    on first recv; a live server simply has nothing to say yet, so the recv
    times out — which IS the healthy signal.

    Detects DEADNESS only (and pays `timeout` per cache-miss probe of a
    healthy backend). A saturated-but-alive backend looks healthy here; use
    the protocol-aware pg_probe/http_probe to shed those too."""
    try:
        with socket.create_connection(addr, timeout=1.0) as s:
            s.settimeout(timeout)
            try:
                return bool(s.recv(1))  # unsolicited banner: alive
            except socket.timeout:
                return True  # connected and silent: alive
    except OSError:
        return False


def pg_probe(addr, timeout: float = 1.0) -> bool:
    """pgwire round-trip: SSLRequest → healthy servers answer b"N". A
    saturated backend (max_connections) answers an ErrorResponse instead and
    is skipped — shedding happens HERE, before a doomed splice."""
    try:
        with socket.create_connection(addr, timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(struct.pack(">II", 8, 80877103))
            return s.recv(1) == b"N"
    except OSError:
        return False


def http_probe(addr, timeout: float = 1.0) -> bool:
    """HTTP round-trip against the readiness endpoint."""
    try:
        with socket.create_connection(addr, timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(b"GET /api/readyz HTTP/1.0\r\n\r\n")
            head = s.recv(16)
            return head.startswith(b"HTTP/1.") and b"200" in head
    except OSError:
        return False


class Balancer:
    def __init__(
        self,
        backends: list[tuple],
        host: str = "127.0.0.1",
        port: int = 0,
        probe=None,
        probe_ttl: float = 1.0,
    ):
        # normalize to tuples once: health cache and probe locks key on the
        # address, and list-typed addrs are unhashable
        self.backends = [tuple(a) for a in backends]
        self.probe = probe or recv_probe
        self.probe_ttl = probe_ttl
        self._health: dict[tuple, tuple[bool, float]] = {}  # addr -> (ok, until)
        # single-flight per backend: a connection burst after TTL expiry
        # must not fan out into a probe storm against the same address
        self._probe_locks: dict[tuple, threading.Lock] = {
            tuple(a): threading.Lock() for a in self.backends
        }
        # counters are bumped from concurrent proxy threads; += is not atomic
        self._stats_lock = threading.Lock()
        self.skipped_backends = 0  # probes that ruled a backend out
        self.shed_connections = 0  # clients closed with no healthy backend
        self._rr = itertools.count()
        self._stop = threading.Event()
        self.srv = socket.create_server((host, port))
        self.srv.listen(64)
        # accept() here is not interrupted by close (ROADMAP known facts):
        # the timeout wakes the loop so the stop flag actually stops it
        self.srv.settimeout(0.5)
        self.port = self.srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _bump(self, name: str) -> None:
        with self._stats_lock:
            setattr(self, name, getattr(self, name) + 1)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._proxy, args=(conn,), daemon=True).start()

    def _healthy(self, addr) -> bool:
        """Probe with a short-TTL cache and per-backend single-flight:
        concurrent pickers coalesce onto one probe, then read its result."""
        lock = self._probe_locks.setdefault(tuple(addr), threading.Lock())
        with lock:
            now = time.monotonic()
            cached = self._health.get(addr)
            if cached is not None and cached[1] > now:
                return cached[0]
            ok = self.probe(addr)
            self._health[addr] = (ok, now + self.probe_ttl)
            return ok

    def _pick_backend(self):
        # round-robin with failover: try every backend once, but only after
        # a request/response round-trip proves it answers (bare connect
        # succeeds on dead ports in this sandbox)
        n = len(self.backends)
        start = next(self._rr)
        for k in range(n):
            addr = self.backends[(start + k) % n]
            if not self._healthy(addr):
                self._bump("skipped_backends")
                continue
            try:
                return socket.create_connection(addr, timeout=5)
            except OSError:
                lock = self._probe_locks.setdefault(tuple(addr), threading.Lock())
                with lock:  # same lock as _healthy: no stale-overwrite race
                    self._health[addr] = (
                        False, time.monotonic() + self.probe_ttl
                    )
                self._bump("skipped_backends")
                continue
        return None

    def _proxy(self, client: socket.socket):
        upstream = self._pick_backend()
        if upstream is None:
            # every backend dead/saturated: shed cleanly instead of hanging
            self._bump("shed_connections")
            client.close()
            return

        def pump(src, dst):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        threading.Thread(target=pump, args=(client, upstream), daemon=True).start()
        pump(upstream, client)
        client.close()
        upstream.close()

    def close(self):
        self._stop.set()
        self.srv.close()
