"""balancerd — stateless ingress router.

The analogue of the reference's `mz-balancerd` (src/balancerd/src/lib.rs:9-12):
a connection-level TCP proxy that spreads pgwire/HTTP clients across backend
environments. No protocol awareness needed — it splices bytes both ways and
removes itself from the failure story (stateless, restartable).
"""

from __future__ import annotations

import itertools
import socket
import threading


class Balancer:
    def __init__(self, backends: list[tuple], host: str = "127.0.0.1", port: int = 0):
        self.backends = list(backends)
        self._rr = itertools.count()
        self.srv = socket.create_server((host, port))
        self.srv.listen(64)
        self.port = self.srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._proxy, args=(conn,), daemon=True).start()

    def _pick_backend(self):
        # round-robin with failover: try every backend once
        n = len(self.backends)
        start = next(self._rr)
        for k in range(n):
            addr = self.backends[(start + k) % n]
            try:
                return socket.create_connection(addr, timeout=5)
            except OSError:
                continue
        return None

    def _proxy(self, client: socket.socket):
        upstream = self._pick_backend()
        if upstream is None:
            client.close()
            return

        def pump(src, dst):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        threading.Thread(target=pump, args=(client, upstream), daemon=True).start()
        pump(upstream, client)
        client.close()
        upstream.close()

    def close(self):
        self.srv.close()
