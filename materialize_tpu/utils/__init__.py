from .native import advance_times_host, consolidate_host, get_native

__all__ = ["advance_times_host", "consolidate_host", "get_native"]
