"""ctypes binding + on-demand build of the native host kernels.

The C++ sources live in native/ and compile to a cached .so with g++ on first
use (no pybind11 — plain C ABI, per the environment's toolchain constraints).
Falls back to pure NumPy implementations when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "consolidate.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libmzt_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        if os.path.exists(_SO) and (
            not os.path.exists(_SRC)
            or os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return True
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True,
            capture_output=True,
        )
        return True
    except (subprocess.CalledProcessError, OSError):
        # no compiler / read-only tree / stripped sources: NumPy fallback
        return False


def get_native():
    """The loaded native library, or None (NumPy fallback)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        lib = ctypes.CDLL(_SO)
        lib.mzt_consolidate.restype = ctypes.c_int64
        lib.mzt_consolidate.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
        ]
        lib.mzt_advance_times.restype = None
        lib.mzt_advance_times.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.c_uint64,
        ]
        _lib = lib
        return _lib


def consolidate_host(cols: dict) -> dict:
    """Consolidate host columnar updates {'c0':…, 'times':…, 'diffs':…}.

    Columns are first canonicalized to 64-bit integer views (floats become
    bit patterns with -0.0 folded and NaN — the float NULL sentinel —
    canonicalized so NULL rows merge; narrower ints widen), mirroring the
    device `value_view`. The native kernel then handles every layout.
    """
    data_keys = sorted(k for k in cols if k not in ("times", "diffs"))
    n = int(len(cols["times"]))
    if n == 0:
        return cols
    restore: dict = {}
    canon = {"times": cols["times"], "diffs": cols["diffs"]}
    for k in data_keys:
        a = np.asarray(cols[k])
        if a.dtype.kind == "f":
            f = a.astype(np.float32, copy=True)
            f[f == 0.0] = np.float32(0.0)
            f[np.isnan(f)] = np.float32(np.nan)
            canon[k] = f.view(np.uint32).astype(np.int64)
            restore[k] = ("f32", a.dtype)
        elif a.dtype.kind in "iub" and a.dtype.itemsize < 8:
            canon[k] = a.astype(np.int64)
            restore[k] = ("cast", a.dtype)
        else:
            canon[k] = a
    out = _consolidate_host_64(canon, data_keys, n)
    for k, (kind, dt) in restore.items():
        if kind == "f32":
            out[k] = out[k].astype(np.uint32).view(np.float32).astype(dt)
        else:
            out[k] = out[k].astype(dt)
    return out


def _consolidate_host_64(cols: dict, data_keys, n: int) -> dict:
    lib = get_native()
    ok_64 = all(cols[k].dtype.itemsize == 8 and cols[k].dtype.kind in "iu" for k in data_keys)
    if lib is not None and ok_64:
        # exactly one copy in (native kernel mutates), viewed as u64 bit
        # patterns so row order matches the NumPy fallback bit for bit
        work = [
            np.array(cols[k], dtype=np.int64, copy=True)
            if cols[k].dtype.kind == "i"
            else np.array(cols[k], dtype=np.uint64, copy=True).view(np.int64)
            for k in data_keys
        ]
        times = np.array(cols["times"], dtype=np.uint64, copy=True)
        diffs = np.array(cols["diffs"], dtype=np.int64, copy=True)
        ptrs = (ctypes.POINTER(ctypes.c_int64) * len(work))(
            *[w.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) for w in work]
        )
        m = lib.mzt_consolidate(
            ptrs,
            len(work),
            times.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            diffs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n,
        )
        out = {}
        for k, w in zip(data_keys, work):
            sliced = w[:m].copy()  # detach from the full-size buffer
            out[k] = sliced if cols[k].dtype.kind == "i" else sliced.view(cols[k].dtype)
        out["times"] = times[:m].copy()
        out["diffs"] = diffs[:m].copy()
        return out
    return _consolidate_numpy(cols, data_keys)


def _consolidate_numpy(cols: dict, data_keys) -> dict:
    # canonical row order must match the native kernel bit for bit: data
    # columns compare as signed i64 bit patterns, times as u64
    def sort_view(a):
        if a.dtype.itemsize == 8 and a.dtype.kind == "u":
            return a.view(np.int64)
        return a

    arrays = [sort_view(cols[k]) for k in data_keys] + [cols["times"]]
    order = np.lexsort(tuple(reversed(arrays)))
    acc: dict = {}
    times = cols["times"]
    diffs = cols["diffs"]
    for i in order:
        key = tuple(cols[k][i].item() for k in data_keys) + (times[i].item(),)
        acc[key] = acc.get(key, 0) + int(diffs[i])
    rows = [(k, d) for k, d in acc.items() if d != 0]
    n = len(rows)
    out = {k: np.empty(n, dtype=cols[k].dtype) for k in data_keys}
    out["times"] = np.empty(n, dtype=np.uint64)
    out["diffs"] = np.empty(n, dtype=np.int64)
    for i, (key, d) in enumerate(rows):
        for j, k in enumerate(data_keys):
            out[k][i] = key[j]
        out["times"][i] = key[-1]
        out["diffs"][i] = d
    return out


def advance_times_host(times: np.ndarray, since: int) -> np.ndarray:
    lib = get_native()
    if lib is not None and times.dtype == np.uint64:
        t = np.ascontiguousarray(times).copy()
        lib.mzt_advance_times(
            t.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(t), since
        )
        return t
    return np.maximum(times, np.uint64(since))
