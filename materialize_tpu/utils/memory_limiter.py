"""Process memory watchdog.

The analogue of the reference's memory limiter
(src/compute/src/memory_limiter.rs:9-12: a process memory+swap watchdog that
intervenes before the OOM killer does). Reads RSS from /proc/self/statm
(no psutil dependency); the coordinator checks it on every commit and refuses
further writes past the hard limit — failing the statement beats losing the
process.
"""

from __future__ import annotations

import os

from ..obs import log as obs_log

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_log = obs_log.get_logger("memory")


def rss_mb() -> float:
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        return int(parts[1]) * _PAGE / (1024 * 1024)
    except (OSError, IndexError, ValueError):
        return 0.0


class MemoryLimiter:
    def __init__(self, limit_mb: int = 0, soft_frac: float = 0.9):
        self.limit_mb = limit_mb
        self.soft_frac = soft_frac
        self._warned = False

    def check(self) -> None:
        """Raise past the hard limit; warn once past the soft limit."""
        if self.limit_mb <= 0:
            return
        rss = rss_mb()
        if rss > self.limit_mb:
            raise MemoryError(
                f"memory limiter: RSS {rss:.0f} MiB exceeds limit {self.limit_mb} MiB"
            )
        if rss > self.limit_mb * self.soft_frac and not self._warned:
            self._warned = True
            _log.warn(
                "RSS above soft limit",
                rss_mb=round(rss),
                soft_frac=self.soft_frac,
                limit_mb=self.limit_mb,
            )
