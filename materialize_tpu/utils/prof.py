"""CPU/heap profiling endpoints — the reference's mz-prof analogue.

The reference serves pprof flamegraphs and jemalloc heap profiles from
environmentd/clusterd HTTP servers (src/prof/src/http.rs). Here:

- `/prof/cpu?seconds=S` — a py-spy-style SAMPLING profiler: every ~5 ms it
  snapshots every thread's Python stack (`sys._current_frames`, no tracing
  overhead on the profiled code) and returns collapsed "folded stack"
  lines (`a;b;c count`) — the flamegraph.pl / speedscope input format.
- `/prof/heap` — tracemalloc top allocation sites (started lazily on first
  request; the text notes the start point since earlier allocations are
  invisible to it).

Both are plain text, safe to hit in production (bounded duration/size).
"""

from __future__ import annotations

import sys
import threading
import time


def cpu_profile_folded(seconds: float = 1.0, interval: float = 0.005) -> str:
    """Sample all thread stacks for `seconds`; return folded-stack lines."""
    me = threading.get_ident()
    counts: dict[str, int] = {}
    deadline = time.perf_counter() + max(0.05, seconds)
    n_samples = 0
    while time.perf_counter() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            while f is not None and len(parts) < 64:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
            if parts:
                key = ";".join(reversed(parts))
                counts[key] = counts.get(key, 0) + 1
        n_samples += 1
        time.sleep(interval)
    lines = [f"# {n_samples} samples over {seconds}s, {len(counts)} distinct stacks"]
    for stack, n in sorted(counts.items(), key=lambda kv: -kv[1]):
        lines.append(f"{stack} {n}")
    return "\n".join(lines) + "\n"


_heap_started_at: float | None = None


def heap_profile_text(top: int = 40) -> str:
    """Top allocation sites since tracemalloc started (lazily, first call)."""
    import tracemalloc

    global _heap_started_at
    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        _heap_started_at = time.time()
        return (
            "# tracemalloc started now; allocations BEFORE this point are "
            "invisible — request again after some work\n"
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [
        f"# tracemalloc since {time.strftime('%H:%M:%S', time.localtime(_heap_started_at or 0))}"
        f", traced total {total / 1e6:.1f} MB, top {len(stats)} sites"
    ]
    for s in stats:
        fr = s.traceback[0]
        lines.append(
            f"{fr.filename.rsplit('/', 1)[-1]}:{fr.lineno} "
            f"{s.size / 1024:.0f} KiB in {s.count} blocks"
        )
    return "\n".join(lines) + "\n"
