"""Tracing: structured spans with a dynamic filter, queryable in SQL.

The analogue of the reference's tracing stack (mz-tracing +
orchestrator-tracing, doc/developer/tracing.md): spans record wall-clock
durations into a ring buffer; `log_filter` (an ALTER SYSTEM-settable dyncfg in
the reference) gates stderr emission; recent spans surface through the
`mz_trace_spans` introspection relation instead of an OpenTelemetry exporter.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass


@dataclass
class Span:
    id: int
    parent: int
    name: str
    start_ns: int
    duration_ns: int = -1  # -1 while open


class Tracer:
    def __init__(self, capacity: int = 2048):
        self.spans: deque[Span] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._local = threading.local()
        self.stderr_level: str = "off"  # off | info | debug

    def set_filter(self, level: str) -> None:
        self.stderr_level = level

    @contextmanager
    def span(self, name: str):
        parent = getattr(self._local, "current", 0)
        s = Span(next(self._ids), parent, name, time.time_ns())
        self._local.current = s.id
        try:
            yield s
        finally:
            s.duration_ns = time.time_ns() - s.start_ns
            self._local.current = parent
            self.spans.append(s)
            if self.stderr_level in ("info", "debug"):
                print(
                    f"[trace] {name} {s.duration_ns/1e6:.2f}ms (span {s.id}<-{s.parent})",
                    file=sys.stderr,
                )

    def recent(self, n: int = 256) -> list[Span]:
        return list(self.spans)[-n:]


TRACER = Tracer()
span = TRACER.span
