"""Back-compat shim: the tracer moved to obs/spans.py (the observability
package), growing cross-process trace contexts on the way. Importers of
``utils.tracing`` keep working; new code should import from ``..obs.spans``.
"""

from ..obs.spans import TRACER, Span, Tracer, span  # noqa: F401
