"""Drive materialize_tpu end-to-end at its package boundary, on real TPU.

Scenario: a stream of auction bids arrives in ticks; we incrementally maintain
  (1) SUM(amount), COUNT(*) per auction            (accumulable reduce)
  (2) bids joined with auctions on auction_id       (linear join, 3-term form)
  (3) top-1 bid per auction                         (topk kernel)
and cross-check the integrated outputs against a brute-force recompute.
"""
import numpy as np
import jax

import materialize_tpu  # noqa: F401  (enables x64)
from materialize_tpu.arrangement import Arrangement, arrange_batch
from materialize_tpu.expr import Column, Literal
from materialize_tpu.ops import consolidate
from materialize_tpu.ops.join import join_against
from materialize_tpu.ops.reduce import AccumState, AggregateExpr, accumulable_step
from materialize_tpu.ops.topk import TopKPlan, topk_step
from materialize_tpu.repr import UpdateBatch, bucket_cap

print("devices:", jax.devices())

rng = np.random.default_rng(42)

# auctions: (id, seller) static-ish; bids: (id, auction_id, amount) streaming
n_auctions = 20
auc_id = np.arange(n_auctions, dtype=np.int64)
auc_seller = rng.integers(100, 110, n_auctions).astype(np.int64)

A_arr = Arrangement(key_cols=(0,))
B_arr = Arrangement(key_cols=(1,))  # bids keyed by auction_id
topk_arr = Arrangement(key_cols=(1,))
sumcount_state = AccumState.empty(
    8, (np.dtype(np.int64),), (np.dtype(np.int64), np.dtype(np.int64))
)
AGGS = (AggregateExpr("sum", Column(2)), AggregateExpr("count", Literal(1)))
plan = TopKPlan(group_cols=(1,), order_by=((2, True),), limit=1)

dA0 = arrange_batch(
    UpdateBatch.build((), (auc_id, auc_seller), [0] * n_auctions, [1] * n_auctions), (0,)
)
A_arr.insert(dA0, already_keyed=True)

sum_out, join_out, topk_out = {}, {}, {}
all_bids = {}
bid_id = 0
for tick in range(1, 8):
    n = int(rng.integers(5, 40))
    ids = np.arange(bid_id, bid_id + n, dtype=np.int64)
    bid_id += n
    aucs = rng.integers(0, n_auctions, n).astype(np.int64)
    amts = rng.integers(1, 1000, n).astype(np.int64)
    diffs = [1] * n
    # occasionally retract an old bid
    retract = [b for b in list(all_bids) if rng.random() < 0.05][:5]
    for b in retract:
        ids = np.append(ids, b[0]); aucs = np.append(aucs, b[1]); amts = np.append(amts, b[2])
        diffs.append(-1)
        del all_bids[b]
    for i in range(n):
        all_bids[(int(ids[i]), int(aucs[i]), int(amts[i]))] = 1

    delta = UpdateBatch.build((), (ids, aucs, amts), [tick] * len(diffs), diffs)

    # (1) reduce
    sumcount_state, out, _errs = accumulable_step(sumcount_state, delta, (1,), AGGS, tick)
    sumcount_state = sumcount_state.with_capacity(bucket_cap(int(sumcount_state.count())))
    for d, _t, df in out.to_rows():
        sum_out[d] = sum_out.get(d, 0) + df

    # (2) join dBids ⋈ Auctions (auctions static this run)
    dB = arrange_batch(delta, (1,))
    for ob in join_against(dB, A_arr.batches):
        for d, _t, df in ob.to_rows():
            join_out[d] = join_out.get(d, 0) + df
    B_arr.insert(dB, already_keyed=True)

    # (3) top-1 per auction
    dT = arrange_batch(delta, (1,))
    out = topk_step(topk_arr, dT, plan, tick)
    for d, _t, df in out.to_rows():
        topk_out[d] = topk_out.get(d, 0) + df

# ---- oracle checks ----
sum_out = {k: v for k, v in sum_out.items() if v != 0}
join_out = {k: v for k, v in join_out.items() if v != 0}
topk_out = {k: v for k, v in topk_out.items() if v != 0}

want_sum = {}
for (bid, auc, amt) in all_bids:
    s, c = want_sum.get(auc, (0, 0))
    want_sum[auc] = (s + amt, c + 1)
assert sum_out == {(a, s, c): 1 for a, (s, c) in want_sum.items()}, "SUM/COUNT mismatch"

want_join = {}
for (bid, auc, amt) in all_bids:
    want_join[(bid, auc, amt, auc, int(auc_seller[auc]))] = 1
assert join_out == want_join, "JOIN mismatch"

# tie-break: engine uses remaining cols ascending; mimic: highest amt, then smallest id
best2 = {}
for (bid, auc, amt) in sorted(all_bids, key=lambda r: (r[1], -r[2], r[0])):
    if auc not in best2:
        best2[auc] = (bid, auc, amt)
want_top2 = {v: 1 for v in best2.values()}
assert topk_out == want_top2, f"TOPK mismatch: {topk_out} != {want_top2}"

print("bids live:", len(all_bids), "| groups:", len(want_sum))
print("SUM/COUNT OK | JOIN OK | TOP1 OK — all maintained incrementally over 7 ticks")
