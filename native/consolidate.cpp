// Native host kernels for the persist/storage runtime.
//
// The reference's runtime is native end to end (Rust + C deps: jemalloc,
// RocksDB, libdecnumber — SURVEY.md §2f); in this build the TPU data plane is
// XLA and the *host* runtime hot loops are C++ behind a C ABI (ctypes
// binding, no pybind11 dependency). This file: columnar consolidation —
// sort updates by (data columns, time) and sum diffs of identical rows —
// used by persist compaction and host-side batch maintenance
// (differential's consolidate_updates, host edition).
//
// Layout: all columns are 64-bit words (i64/u64 bit patterns; the engine's
// host payloads are fixed-width 64-bit columns). In-place: rows are permuted,
// merged, and compacted to the front; returns the new live row count.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// cols: ncols pointers to n-element i64 data columns
// times: n u64 timestamps, diffs: n i64 multiplicities
// returns: number of surviving rows (compacted to the front of every array)
int64_t mzt_consolidate(int64_t** cols, int32_t ncols, uint64_t* times,
                        int64_t* diffs, int64_t n) {
  if (n <= 0) return 0;
  std::vector<int64_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    for (int32_t c = 0; c < ncols; ++c) {
      if (cols[c][a] != cols[c][b]) return cols[c][a] < cols[c][b];
    }
    return times[a] < times[b];
  });

  auto same = [&](int64_t a, int64_t b) {
    for (int32_t c = 0; c < ncols; ++c) {
      if (cols[c][a] != cols[c][b]) return false;
    }
    return times[a] == times[b];
  };

  // merge runs into scratch, skipping rows whose diffs cancel
  std::vector<std::vector<int64_t>> out_cols(ncols);
  std::vector<uint64_t> out_times;
  std::vector<int64_t> out_diffs;
  for (int32_t c = 0; c < ncols; ++c) out_cols[c].reserve(n);
  out_times.reserve(n);
  out_diffs.reserve(n);

  int64_t i = 0;
  while (i < n) {
    int64_t j = i;
    int64_t total = 0;
    while (j < n && same(idx[i], idx[j])) {
      total += diffs[idx[j]];
      ++j;
    }
    if (total != 0) {
      for (int32_t c = 0; c < ncols; ++c) out_cols[c].push_back(cols[c][idx[i]]);
      out_times.push_back(times[idx[i]]);
      out_diffs.push_back(total);
    }
    i = j;
  }

  int64_t m = static_cast<int64_t>(out_times.size());
  for (int32_t c = 0; c < ncols; ++c) {
    std::memcpy(cols[c], out_cols[c].data(), m * sizeof(int64_t));
  }
  std::memcpy(times, out_times.data(), m * sizeof(uint64_t));
  std::memcpy(diffs, out_diffs.data(), m * sizeof(int64_t));
  return m;
}

// advance all times to at least `since` (logical compaction), in place
void mzt_advance_times(uint64_t* times, int64_t n, uint64_t since) {
  for (int64_t i = 0; i < n; ++i) {
    if (times[i] < since) times[i] = since;
  }
}

}  // extern "C"
