#!/usr/bin/env python
"""Thin shim over `materialize_tpu.analysis` — the metrics-coherence rule.

The functional check itself (boot a Coordinator, run real SQL, render the
/metrics exposition, cross-check every bumped counter and every
INTROSPECTION_TABLES arity) lives in
materialize_tpu/analysis/passes/metrics_rule.py; this wrapper keeps the
historical CLI (`env JAX_PLATFORMS=cpu python scripts/lint_metrics.py`)
and the `lint()` / `overload_counter_names()` / `sharing_counter_names()`
API that tests/test_lint_metrics.py exercises. Prefer
`python -m materialize_tpu.analysis --rules metrics-coherence` directly.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from materialize_tpu.analysis.passes.metrics_rule import (  # noqa: E402
    REQUIRED_FAMILIES,
    overload_counter_names as _overload_counter_names,
    sharing_counter_names as _sharing_counter_names,
    lint as _lint,
)

__all__ = [
    "REQUIRED_FAMILIES",
    "overload_counter_names",
    "sharing_counter_names",
    "lint",
    "main",
]


def overload_counter_names() -> set[str]:
    return _overload_counter_names(REPO)


def sharing_counter_names() -> set[str]:
    return _sharing_counter_names(REPO)


def lint() -> list[str]:
    return _lint(REPO)


def main() -> int:
    vs = lint()
    for v in vs:
        print(v, file=sys.stderr)
    if vs:
        print(f"lint_metrics: {len(vs)} violation(s)", file=sys.stderr)
        return 1
    from materialize_tpu.adapter.introspection import INTROSPECTION_TABLES

    print(
        f"lint_metrics: OK ({len(INTROSPECTION_TABLES)} relations, "
        f"{len(overload_counter_names())} overload counters checked)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
