#!/usr/bin/env python
"""Lint: every counter the engine maintains must be observable.

Two kinds of silent observability rot this guards against:

1. a counter bumped somewhere in the engine — an OverloadStats
   ``bump()``/``record_max()`` literal, a trace-manager sharing stat, a
   persist/mesh/controller registry family — that never shows up in the
   ``/metrics`` exposition: the decision happened, nobody can see it;
2. an ``INTROSPECTION_TABLES`` entry whose populator is missing or emits rows
   of the wrong arity — the catalog advertises a relation that faults (or
   lies) the day someone actually selects from it.

The check is functional, not purely textual: it boots an in-memory
coordinator, drives one table + materialized view + peek through it, greps
the source tree for counter-name literals, then renders ``metrics_text()``
and materializes every introspection relation through real SQL.

Run: python scripts/lint_metrics.py   (exit 1 on violations; wrapped as a
tier-1 test in tests/test_lint_metrics.py so CI enforces it).
"""

from __future__ import annotations

import os
import re
import sys
import threading
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "materialize_tpu"

# registry families registered at module import by the subsystems the issue
# names: persist op latencies/counters, mesh exchange volume, controller
# heartbeat RTTs, coordinator tick histograms. render() emits HELP/TYPE even
# for families with no samples yet, so absence here means the registration
# itself was dropped.
REQUIRED_FAMILIES = (
    "mzt_persist_ops_total",
    "mzt_persist_op_duration_ns",
    "mzt_persist_blob_bytes_total",
    "mzt_mesh_exchange_frames_total",
    "mzt_mesh_exchange_bytes_total",
    "mzt_heartbeat_rtt_seconds",
    "mzt_dataflow_tick_duration_ns",
)

_BUMP = re.compile(r'(?:\.bump|\.record_max)\(\s*"([a-z_]+)"')
_SHARING = re.compile(r'self\.stats\[\s*"([a-z_]+)"\s*\]')


def overload_counter_names() -> set[str]:
    """Every OverloadStats counter name bumped anywhere in the package."""
    names: set[str] = set()
    for path in sorted(PKG.rglob("*.py")):
        names.update(_BUMP.findall(path.read_text()))
    return names


def sharing_counter_names() -> set[str]:
    return set(_SHARING.findall((PKG / "arrangement" / "trace_manager.py").read_text()))


def lint() -> list[str]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, str(REPO))

    # import the subsystems whose module-level registrations we assert on
    import materialize_tpu.cluster.controller  # noqa: F401
    import materialize_tpu.cluster.mesh  # noqa: F401
    import materialize_tpu.persist.location  # noqa: F401
    from materialize_tpu.adapter import Coordinator
    from materialize_tpu.adapter.introspection import (
        INTROSPECTION_TABLES,
        introspection_rows,
    )
    from materialize_tpu.frontend.http_server import metrics_text

    violations: list[str] = []
    coord = Coordinator()
    coord.execute("CREATE TABLE lint_t (a int)")
    coord.execute("INSERT INTO lint_t VALUES (1), (2)")
    coord.execute(
        "CREATE MATERIALIZED VIEW lint_mv AS"
        " SELECT a, count(*) AS n FROM lint_t GROUP BY a"
    )
    coord.execute("SELECT * FROM lint_mv")

    # seed every statically-known overload counter at 0 so the exposition
    # must carry it even before the first real bump
    for name in sorted(overload_counter_names()):
        coord.overload.bump(name, 0)

    text = metrics_text(coord, threading.Lock())

    for name in sorted(overload_counter_names()):
        if f'mzt_overload_counter{{name="{name}"}}' not in text:
            violations.append(
                f"overload counter {name!r} is bumped in the source but absent "
                "from the /metrics exposition (mzt_overload_counter)"
            )
    for name in sorted(sharing_counter_names()):
        if f'mzt_trace_sharing_counter{{name="{name}"}}' not in text:
            violations.append(
                f"trace-sharing counter {name!r} is maintained by the trace "
                "manager but absent from /metrics (mzt_trace_sharing_counter)"
            )
    for fam in REQUIRED_FAMILIES:
        if f"# TYPE {fam} " not in text:
            violations.append(
                f"registry family {fam!r} missing from /metrics — its "
                "registering module was dropped or the name changed"
            )

    for name, desc in sorted(INTROSPECTION_TABLES.items()):
        arity = len(desc.columns)
        try:
            rows = introspection_rows(coord, name)
        except Exception as e:  # missing/broken populator
            violations.append(f"{name}: populator raised {type(e).__name__}: {e}")
            continue
        for r in rows:
            if len(r) != arity:
                violations.append(
                    f"{name}: populator row arity {len(r)} != declared "
                    f"schema arity {arity} (row: {r!r})"
                )
                break
        try:  # the full SQL path: virtual collection snapshot + decode
            coord.execute(f"SELECT * FROM {name}")
        except Exception as e:
            violations.append(
                f"{name}: SELECT * faulted with {type(e).__name__}: {e}"
            )
    return violations


def main() -> int:
    vs = lint()
    for v in vs:
        print(v, file=sys.stderr)
    if vs:
        print(f"lint_metrics: {len(vs)} violation(s)", file=sys.stderr)
        return 1
    from materialize_tpu.adapter.introspection import INTROSPECTION_TABLES

    print(
        f"lint_metrics: OK ({len(INTROSPECTION_TABLES)} relations, "
        f"{len(overload_counter_names())} overload counters checked)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
