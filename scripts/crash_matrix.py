#!/usr/bin/env python
"""Whole-process crash-recovery matrix for the durability substrate.

The storage-plane analogue of the chaos tier (tests/test_chaos.py): a
canonical workload — tables + an upsert file source + an append-only file
source + materialized views + multi-shard txn-wal commits — runs under a
seeded `CrashPlan` (persist/crashpoints.py) that dies at exactly one
durable-op index k. The matrix sweeps k = 1..N over the durable-op trace of
a crash-free measurement run and, after every crash, restarts from the same
`data_dir` asserting:

- boot succeeds and the catalog is intact,
- the recovered logical state is byte-identical to one of the crash-free
  run's per-step snapshots — i.e. every crash lands on a statement boundary:
  either the step containing op k committed wholly or not at all,
- `persist.fsck` reports no FATAL findings,
- file sources resume EXACTLY-ONCE across the remap binding: after catch-up
  ticks, source-derived contents equal the crash-free run's final state
  (no duplicates, no gaps),
- (recovery sweep) a SECOND crash injected during `_boot` itself — txn
  apply, rehydration, MV shard reconciliation — still converges on the next
  boot, because boot is re-entrant.

Two modes: `--mode inprocess` simulates the crash with `CrashPointReached`
(BaseException: cleanup `except Exception` handlers stay cold, like a real
crash) and is fast enough for tier-1 subsets; `--mode subprocess` runs the
workload in a child process that `os._exit`s at the crash point — a genuine
whole-process crash with no unwinding at all — shipped via `MZT_CRASH_SPEC`
exactly like the network plane's `MZT_FAULT_SPEC`.

Replay: every sweep prints `CRASH_SEED=<n>`; a failing point reruns exactly
with `CRASH_SEED=<n> python scripts/crash_matrix.py --points <k>`.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_SEED = 20260804

# logical relations the state dump captures (dumped only once created)
RELATIONS = ("accounts", "prices", "events", "mv_bal", "ev_counts")


def _force_cpu() -> None:
    """Child-process guard: tests must never touch the real TPU pool (the
    same dance as tests/conftest.py — the axon plugin registers at
    interpreter startup via sitecustomize)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        for _name in ("axon", "tpu"):
            _xb._backend_factories.pop(_name, None)
    except Exception:
        pass


# -- the canonical workload ---------------------------------------------------
def write_source_files(src_dir: str) -> None:
    """Deterministic external-source fixtures: an upsert keyed feed with an
    overwrite and a tombstone, and an append-only event feed."""
    os.makedirs(src_dir, exist_ok=True)
    prices = [
        {"sym": "AAA", "px": 10},
        {"sym": "BBB", "px": 20},
        {"sym": "CCC", "px": 30},
        {"sym": "AAA", "px": 11},  # overwrite
        {"sym": "BBB", "px": None},  # tombstone
        {"sym": "DDD", "px": 40},
    ]
    events = [{"id": i, "kind": "put" if i % 2 else "get"} for i in range(6)]
    with open(os.path.join(src_dir, "prices.jsonl"), "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in prices))
    with open(os.path.join(src_dir, "events.jsonl"), "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in events))


def workload_steps(src_dir: str) -> list:
    """(name, action) pairs; actions are SQL strings or coordinator closures.
    Each step is one statement/tick — the atomicity unit the matrix checks.
    Multi-shard txn commits come from advance() ticks that ingest BOTH file
    sources (+ their remap shards) in one atomic commit."""
    prices = os.path.join(src_dir, "prices.jsonl")
    events = os.path.join(src_dir, "events.jsonl")
    return [
        ("create-accounts", "CREATE TABLE accounts (id int, balance int)"),
        ("insert-accounts", "INSERT INTO accounts VALUES (1, 100), (2, 200), (3, 300)"),
        (
            "create-prices",
            f"CREATE SOURCE prices (sym text, px int) FROM FILE '{prices}' "
            "(FORMAT JSON) ENVELOPE UPSERT (KEY (sym))",
        ),
        (
            "create-events",
            f"CREATE SOURCE events (id int, kind text) FROM FILE '{events}' "
            "(FORMAT JSON)",
        ),
        (
            "create-mv-bal",
            "CREATE MATERIALIZED VIEW mv_bal AS "
            "SELECT sum(balance) AS total FROM accounts",
        ),
        (
            "create-mv-ev",
            "CREATE MATERIALIZED VIEW ev_counts AS "
            "SELECT kind, count(*) AS n FROM events GROUP BY kind",
        ),
        ("insert-late", "INSERT INTO accounts VALUES (4, 50)"),
        ("tick-1", lambda c: c.advance(2)),
        ("delete", "DELETE FROM accounts WHERE id = 2"),
        ("tick-2", lambda c: c.advance(2)),
        ("update", "UPDATE accounts SET balance = balance + 7 WHERE id = 1"),
        ("tick-3", lambda c: c.advance(4)),
        ("tick-4", lambda c: c.advance(4)),
    ]


def state_dump(coord) -> dict:
    """The workload's logical state: catalog names + sorted relation rows.
    Pure data (ints/strings), so json round-trips are byte-identical."""
    out = {
        "catalog": sorted(
            n for n, it in coord.catalog.items.items() if it.kind != "introspection"
        )
    }
    for name in RELATIONS:
        it = coord.catalog.items.get(name)
        if it is None or it.kind not in ("table", "source", "materialized_view"):
            continue
        out[name] = sorted(coord.execute(f"SELECT * FROM {name}").rows)
    return json.loads(json.dumps(out))  # tuples -> lists, like the snapshots


def empty_dump() -> dict:
    return {"catalog": []}


def run_workload(data_dir: str, src_dir: str):
    """Run the canonical workload; returns (snapshots, ops_at_step) where
    ops_at_step[i] = durable-op count after step i (from the installed
    CrashPlan; zeros when none is installed)."""
    from materialize_tpu.adapter import Coordinator
    from materialize_tpu.persist import crashpoints

    coord = Coordinator(data_dir=data_dir)
    snaps, ops_at = [], []
    for _name, action in workload_steps(src_dir):
        if isinstance(action, str):
            coord.execute(action)
        else:
            action(coord)
        snaps.append(state_dump(coord))
        plan = crashpoints.installed_plan()
        ops_at.append(plan.op_count if plan is not None else 0)
    return snaps, ops_at


def catch_up_sources(coord, max_rounds: int = 40) -> None:
    """Drive advance() until every file source has consumed its file."""
    for _ in range(max_rounds):
        srcs = getattr(coord, "file_sources", [])
        if all(
            src.offset >= os.path.getsize(src.spec.path) for src, _g, _u in srcs
        ):
            return
        coord.advance(4)


def mv_shard_divergence(coord) -> list:
    """Compare every MV's DURABLE shard against its recomputed in-memory
    collection (both encoded): the shard is what external readers (clusterd
    hydration, a future replica) see, and a crash between the base-shard
    commit and the derived persist must not leave it short a delta. Returns
    a list of 'mv gid: n rows diverged' strings (empty = consistent)."""
    import numpy as np

    from materialize_tpu.persist.shard import _consolidate_host

    problems = []
    for name, item in coord.catalog.items.items():
        if item.kind != "materialized_view":
            continue
        gid = item.global_id
        m = coord._shard(gid)
        _seq, state = m.fetch_state()
        desired = coord.storage[gid].snapshot(max(coord.oracle.read_ts(), 0))
        h = desired.to_host()
        t = np.uint64(max(int(state.upper), coord.oracle.read_ts(), 1))
        pieces = [
            {
                **{f"c{i}": c for i, c in enumerate(h["vals"])},
                "times": np.full_like(h["times"], t),
                "diffs": h["diffs"],
            }
        ]
        if state.upper > 0:
            for cols in m.snapshot(max(state.upper - 1, 0)):
                cols = dict(cols)
                cols["times"] = np.full_like(cols["times"], t)
                cols["diffs"] = -cols["diffs"]
                pieces.append(cols)
        keys = pieces[0].keys()
        merged = {k: np.concatenate([p[k] for p in pieces]) for k in keys}
        diff = _consolidate_host(merged)
        n = int(len(diff["times"]))
        if n:
            problems.append(f"{name} ({gid}): durable shard diverged by {n} rows")
    return problems


def step_of_op(ops_at: list, k: int) -> int:
    """Index of the workload step whose execution covered durable op k."""
    for i, n in enumerate(ops_at):
        if n >= k:
            return i
    return len(ops_at) - 1


# -- verification ------------------------------------------------------------
def verify_payload(data_dir: str) -> dict:
    """Boot from a (crashed) data_dir and collect every recovery fact the
    judge needs: the recovered state dump, fsck findings, MV shard
    divergence, and the post-catch-up state. Runs in-process for the
    inprocess sweep and inside the verify child for the subprocess sweep —
    ONE collection path, ONE judge (_judge_verify)."""
    from materialize_tpu.adapter import Coordinator
    from materialize_tpu.persist.fsck import fsck_data_dir

    coord = Coordinator(data_dir=data_dir)
    report = fsck_data_dir(data_dir)
    recovered = state_dump(coord)
    mv_problems = mv_shard_divergence(coord)
    catch_up_sources(coord)
    post = state_dump(coord)
    return {
        "recovered": recovered,
        "post": post,
        "mv_divergence": mv_problems,
        "fsck_fatal": [f.detail for f in report.fatal],
        "fsck_findings": [f.as_dict() for f in report.findings],
    }


def verify_recovery(data_dir: str, src_dir: str, snaps: list, ops_at: list,
                    k: int) -> dict:
    """Boot from the crashed data_dir and run the full assertion set.
    Returns a verdict dict; raises nothing (failures land in verdict)."""
    try:
        payload = verify_payload(data_dir)
    except Exception as exc:
        return {
            "k": k, "ok": False,
            "problems": [f"recovery/verification raised: {exc!r}"],
        }
    return _judge_verify(payload, snaps, ops_at, k)


# -- in-process sweep ---------------------------------------------------------
def record_run(work_dir: str, src_dir: str, seed: int):
    """Crash-free measurement run: the op trace + per-step snapshots."""
    from materialize_tpu.persist import crashpoints
    from materialize_tpu.persist.crashpoints import CrashPlan

    write_source_files(src_dir)
    record_dir = os.path.join(work_dir, "record")
    shutil.rmtree(record_dir, ignore_errors=True)  # always a fresh boot
    plan = CrashPlan(seed, crash_at=None)
    crashpoints.install(plan)
    try:
        snaps, ops_at = run_workload(record_dir, src_dir)
    finally:
        crashpoints.install(None)
    return snaps, ops_at, list(plan.trace)


def sweep_inprocess(work_dir: str, seed: int, points=None) -> list:
    from materialize_tpu.persist import crashpoints
    from materialize_tpu.persist.crashpoints import CrashPlan, CrashPointReached

    src_dir = os.path.join(work_dir, "src")
    snaps, ops_at, trace = record_run(work_dir, src_dir, seed)
    n_ops = len(trace)
    verdicts = []
    for k in points if points is not None else range(1, n_ops + 1):
        if not (1 <= k <= n_ops):
            continue
        data_dir = os.path.join(work_dir, f"crash{k}")
        shutil.rmtree(data_dir, ignore_errors=True)
        plan = CrashPlan(seed, crash_at=k)
        crashpoints.install(plan)
        crashed = None
        try:
            run_workload(data_dir, src_dir)
        except CrashPointReached as e:
            crashed = e
        finally:
            crashpoints.install(None)
        if crashed is None:
            verdicts.append(
                {"k": k, "ok": False, "problems": [f"op {k} never crashed"]}
            )
            continue
        v = verify_recovery(data_dir, src_dir, snaps, ops_at, k)
        v["label"], v["shape"] = crashed.label, crashed.shape
        verdicts.append(v)
    return verdicts


def sweep_recovery_crashes(work_dir: str, seed: int, points=None) -> list:
    """Crash-during-recovery matrix: die at a txn-wal commit point (the
    txns-shard CAS, shape=after: durable + unacked), then sweep a SECOND
    seeded crash over recovery's own durable ops; the third boot must
    converge with a clean fsck — `_boot` re-entrancy."""
    from materialize_tpu.adapter import Coordinator
    from materialize_tpu.persist import crashpoints
    from materialize_tpu.persist.crashpoints import CrashPlan, CrashPointReached
    from materialize_tpu.persist.fsck import fsck_data_dir

    src_dir = os.path.join(work_dir, "src")
    snaps, ops_at, trace = record_run(work_dir, src_dir, seed)
    txn_cas = [n for (n, label, key, _d) in trace
               if label == "cas" and key == "shard/txns"]
    if not txn_cas:
        raise RuntimeError("workload produced no txn-wal commit (bad workload)")
    k_star = txn_cas[-1]  # the last multi-shard commit: most state behind it

    crashed_dir = os.path.join(work_dir, "rc-crashed")
    shutil.rmtree(crashed_dir, ignore_errors=True)
    plan = CrashPlan(seed, crash_at=k_star, shape="after")
    crashpoints.install(plan)
    try:
        run_workload(crashed_dir, src_dir)
        raise RuntimeError(f"op {k_star} never crashed")
    except CrashPointReached:
        pass
    finally:
        crashpoints.install(None)

    # measure recovery's own durable-op count on a scratch copy
    probe_dir = os.path.join(work_dir, "rc-probe")
    shutil.rmtree(probe_dir, ignore_errors=True)
    shutil.copytree(crashed_dir, probe_dir)
    plan = CrashPlan(seed, crash_at=None)
    crashpoints.install(plan)
    try:
        Coordinator(data_dir=probe_dir)
    finally:
        crashpoints.install(None)
    m_ops = plan.op_count

    verdicts = []
    for j in points if points is not None else range(1, m_ops + 1):
        if not (1 <= j <= m_ops):
            continue
        data_dir = os.path.join(work_dir, f"rc{j}")
        shutil.rmtree(data_dir, ignore_errors=True)
        shutil.copytree(crashed_dir, data_dir)
        plan = CrashPlan(seed, crash_at=j)
        crashpoints.install(plan)
        crashed = None
        try:
            Coordinator(data_dir=data_dir)
        except CrashPointReached as e:
            crashed = e
        finally:
            crashpoints.install(None)
        v = {"k": k_star, "recovery_op": j, "ok": True, "problems": []}
        if crashed is None:
            # recovery finished before op j — only legal if recovery had
            # fewer ops than the probe (e.g. an earlier crash already
            # applied part of the work); verify convergence anyway
            v["shape"] = "none"
        else:
            v["label"], v["shape"] = crashed.label, crashed.shape
        inner = verify_recovery(data_dir, src_dir, snaps, ops_at, k_star)
        if not inner["ok"]:
            v["ok"] = False
            v["problems"] = inner["problems"]
        report = fsck_data_dir(data_dir)
        if not report.ok:
            v["ok"] = False
            v["problems"].append(
                f"fsck fatal after double-crash recovery: "
                f"{[f.detail for f in report.fatal]}"
            )
        verdicts.append(v)
    return verdicts


# -- subprocess (whole-process) sweep ----------------------------------------
def _child_env(spec: str | None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    from materialize_tpu.persist.crashpoints import ENV_SPEC

    if spec is None:
        env.pop(ENV_SPEC, None)
    else:
        env[ENV_SPEC] = spec
    return env


def _run_child(role: str, data_dir: str, src_dir: str, out_path: str,
               spec: str | None, timeout: float = 600.0) -> int:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child", role,
        "--data-dir", data_dir, "--src-dir", src_dir, "--out", out_path,
    ]
    r = subprocess.run(
        cmd, env=_child_env(spec), cwd=REPO, timeout=timeout,
        capture_output=True, text=True,
    )
    if r.returncode not in (0, 86):
        sys.stderr.write(r.stdout[-2000:] + "\n" + r.stderr[-2000:] + "\n")
    return r.returncode


def sweep_subprocess(work_dir: str, seed: int, points=None) -> list:
    """The genuine whole-process matrix: each crash point is an os._exit in
    a child coordinator; recovery+verification runs in a second child."""
    from materialize_tpu.persist.crashpoints import CRASH_EXIT_CODE, CrashPlan

    src_dir = os.path.join(work_dir, "src")
    write_source_files(src_dir)
    # measurement child: records trace + snapshots crash-free
    trace_path = os.path.join(work_dir, "record.trace")
    out_path = os.path.join(work_dir, "record.json")
    record_dir = os.path.join(work_dir, "record")
    for stale in (trace_path, out_path):
        if os.path.exists(stale):
            os.unlink(stale)  # trace files are append-mode
    shutil.rmtree(record_dir, ignore_errors=True)
    spec = CrashPlan(seed, crash_at=None, hard=True, trace_path=trace_path).to_spec()
    rc = _run_child("workload", record_dir, src_dir, out_path, spec)
    if rc != 0:
        raise RuntimeError(f"crash-free measurement run failed (rc={rc})")
    with open(out_path) as f:
        doc = json.load(f)
    snaps, ops_at = doc["snaps"], doc["ops_at"]
    with open(trace_path) as f:
        n_ops = sum(1 for _ in f)

    verdicts = []
    for k in points if points is not None else range(1, n_ops + 1):
        if not (1 <= k <= n_ops):
            continue
        data_dir = os.path.join(work_dir, f"crash{k}")
        shutil.rmtree(data_dir, ignore_errors=True)
        k_trace = os.path.join(work_dir, f"crash{k}.trace")
        if os.path.exists(k_trace):
            os.unlink(k_trace)
        spec = CrashPlan(seed, crash_at=k, hard=True, trace_path=k_trace).to_spec()
        rc = _run_child("workload", data_dir, src_dir,
                        os.path.join(work_dir, f"crash{k}.json"), spec)
        if rc != CRASH_EXIT_CODE:
            verdicts.append({
                "k": k, "ok": False,
                "problems": [f"workload child exited {rc}, wanted crash"],
            })
            continue
        shape = "?"
        try:
            with open(k_trace) as f:
                last = f.read().strip().splitlines()[-1].split("\t")
            shape = last[3].removeprefix("crash-")
            label = last[1]
        except Exception:
            label = "?"
        vout = os.path.join(work_dir, f"verify{k}.json")
        rc = _run_child("verify", data_dir, src_dir, vout, None)
        if rc != 0:
            verdicts.append({
                "k": k, "ok": False, "label": label, "shape": shape,
                "problems": [f"verify child exited {rc}"],
            })
            continue
        with open(vout) as f:
            child = json.load(f)
        v = _judge_verify(child, snaps, ops_at, k)
        v["label"], v["shape"] = label, shape
        verdicts.append(v)
    return verdicts


def _judge_verify(payload: dict, snaps, ops_at, k: int) -> dict:
    """THE judge: every recovery assertion, applied to a verify payload
    (in-process or from a verify child) — one place to tighten."""
    verdict = {"k": k, "ok": True, "problems": [],
               "fsck_findings": payload.get("fsck_findings", [])}

    def fail(msg):
        verdict["ok"] = False
        verdict["problems"].append(msg)

    if payload["fsck_fatal"]:
        fail(f"fsck fatal: {payload['fsck_fatal']}")
    for problem in payload.get("mv_divergence", []):
        fail(f"durable MV shard inconsistent after recovery: {problem}")
    s = step_of_op(ops_at, k)
    verdict["step"] = s
    allowed = [snaps[s], snaps[s - 1] if s > 0 else empty_dump()]
    if payload["recovered"] not in allowed:
        fail(
            f"recovered state is not a statement-boundary prefix (step {s}): "
            f"{json.dumps(payload['recovered'])[:400]}"
        )
    # exactly-once resume: after catch-up ticks, source-derived contents
    # must equal the crash-free run's final state (a dup shows as extra
    # rows / wrong counts, a gap as missing rows). A crash BEFORE a
    # source's CREATE legitimately leaves it absent.
    final = snaps[-1]
    for rel in ("prices", "events", "ev_counts"):
        if rel in payload["post"] and payload["post"].get(rel) != final.get(rel):
            fail(
                f"{rel} after catch-up != crash-free final (exactly-once "
                f"violated): {payload['post'].get(rel)} vs {final.get(rel)}"
            )
    return verdict


# -- child entry points -------------------------------------------------------
def _child_workload(args) -> None:
    _force_cpu()
    from materialize_tpu.persist import crashpoints

    crashpoints.install_from_env()
    snaps, ops_at = run_workload(args.data_dir, args.src_dir)
    with open(args.out, "w") as f:
        json.dump({"snaps": snaps, "ops_at": ops_at}, f)


def _child_verify(args) -> None:
    _force_cpu()
    from materialize_tpu.persist import crashpoints

    crashpoints.install_from_env()  # set => crash-during-recovery mode
    payload = verify_payload(args.data_dir)
    with open(args.out, "w") as f:
        json.dump(payload, f)


# -- CLI ----------------------------------------------------------------------
def print_verdicts(verdicts: list, seed: int) -> None:
    print(f"CRASH_SEED={seed}")
    print(f"{'k':>4} {'op':<12} {'shape':<7} {'step':>4} verdict")
    for v in verdicts:
        k = v.get("recovery_op", v["k"])
        print(
            f"{k:>4} {v.get('label', '?'):<12} {v.get('shape', '?'):<7} "
            f"{v.get('step', -1):>4} "
            + ("PASS" if v["ok"] else "FAIL: " + "; ".join(v["problems"]))
        )
    bad = [v for v in verdicts if not v["ok"]]
    print(f"{len(verdicts) - len(bad)}/{len(verdicts)} crash points recovered")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("CRASH_SEED", DEFAULT_SEED)))
    p.add_argument("--mode", choices=("inprocess", "subprocess"),
                   default="inprocess")
    p.add_argument("--recovery", action="store_true",
                   help="sweep crash-during-recovery instead of the workload")
    p.add_argument("--points", default=None,
                   help="comma-separated crash-point indices (default: all)")
    p.add_argument("--work-dir", default=None)
    p.add_argument("--child", choices=("workload", "verify"), default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--data-dir", default=None, help=argparse.SUPPRESS)
    p.add_argument("--src-dir", default=None, help=argparse.SUPPRESS)
    p.add_argument("--out", default=None, help=argparse.SUPPRESS)
    args = p.parse_args()

    if args.child == "workload":
        _child_workload(args)
        return 0
    if args.child == "verify":
        _child_verify(args)
        return 0

    _force_cpu()
    import tempfile

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="crash_matrix_")
    points = (
        [int(x) for x in args.points.split(",")] if args.points else None
    )
    if args.recovery:
        verdicts = sweep_recovery_crashes(work_dir, args.seed, points)
    elif args.mode == "subprocess":
        verdicts = sweep_subprocess(work_dir, args.seed, points)
    else:
        verdicts = sweep_inprocess(work_dir, args.seed, points)
    print_verdicts(verdicts, args.seed)
    return 0 if all(v["ok"] for v in verdicts) else 1


if __name__ == "__main__":
    sys.exit(main())
