#!/usr/bin/env python
"""Thin shim over `materialize_tpu.analysis` — the listener-hygiene rule.

The needle set and rationale live in
materialize_tpu/analysis/passes/hygiene.py (this sandbox's network stack
does not interrupt a thread blocked in ``accept()`` when the listener is
closed, so every accept loop needs a timeout + wake-up handler + shutdown
path). This wrapper keeps the historical CLI and the ``check_file(path)``
API that tests/test_overload.py exercises; the registered rule scans the
WHOLE package, this shim's main() keeps the historical frontend/+cluster/
sweep. Prefer `python -m materialize_tpu.analysis --rules listener-hygiene`.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from materialize_tpu.analysis.passes.hygiene import problems_for_text  # noqa: E402

SCAN_DIRS = [
    os.path.join(REPO, "materialize_tpu", "frontend"),
    os.path.join(REPO, "materialize_tpu", "cluster"),
]


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    rel = os.path.relpath(path, REPO)
    return [f"{rel}: {p}" for p in problems_for_text(text)]


def main() -> int:
    problems: list[str] = []
    scanned = 0
    for d in SCAN_DIRS:
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            scanned += 1
            problems.extend(check_file(os.path.join(d, name)))
    if problems:
        print("listener hygiene violations:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"listener hygiene: {scanned} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
