#!/usr/bin/env python
"""Listener hygiene check: every accept loop must be shutdown-capable.

This sandbox's network stack does NOT interrupt a thread blocked in
``accept()`` when the listening socket is closed (doc/ROADMAP.md known
facts) — a raw ``while True: srv.accept()`` loop therefore leaks its thread
forever and can hold the process open. The fix pattern is mechanical, so
this check enforces it: every file under materialize_tpu/frontend/ and
materialize_tpu/cluster/ that calls ``.accept(`` must ALSO

  1. set a timeout on the listener (``settimeout(``) so the loop wakes
     periodically, and
  2. handle ``socket.timeout`` (the wake-up), and
  3. handle ``OSError`` (the closed-listener exit — the shutdown path).

Files using stdlib servers (http.server's serve_forever is selector-driven
and shutdown()-capable) don't contain a literal ``.accept(`` and pass
automatically. Run: python scripts/check_listener_hygiene.py
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = [
    os.path.join(REPO, "materialize_tpu", "frontend"),
    os.path.join(REPO, "materialize_tpu", "cluster"),
]

REQUIRED = {
    "listener timeout": "settimeout(",
    "timeout wake-up handler": "except socket.timeout",
    "closed-listener shutdown path": "except OSError",
}


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if ".accept(" not in text:
        return []
    return [
        f"{os.path.relpath(path, REPO)}: accept loop lacks {what} ({needle!r})"
        for what, needle in REQUIRED.items()
        if needle not in text
    ]


def main() -> int:
    problems: list[str] = []
    scanned = 0
    for d in SCAN_DIRS:
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            scanned += 1
            problems.extend(check_file(os.path.join(d, name)))
    if problems:
        print("listener hygiene violations:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"listener hygiene: {scanned} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
