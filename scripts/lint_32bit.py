#!/usr/bin/env python
"""Lint: the device hot path must stay 32-bit native.

The tick pipeline (ops/, arrangement/, parallel/exchange*.py) carries u32
hashes, u32 time views, and (hi, lo) u32 sort-key pairs end-to-end; the TPU
VPU is a 32-bit machine and every stray 64-bit device dtype reintroduces
X64SplitLow pairs into sorts/probes (the confirmed ~2× tax of the r2
profile). Deliberate 64-bit columns — diffs, SQL bigint data, aggregate
accumulators — are declared ONCE as aliases at the representation boundary
(repr/batch.py: TIME_DTYPE / DIFF_DTYPE / I64_DTYPE) and imported from
there, so this lint simply forbids naming `jnp.int64` / `jnp.uint64` (and
64-bit jnp scalar constructors) inside the hot-path modules.

Run: python scripts/lint_32bit.py   (exit 1 on violations; also wrapped as a
tier-1 test in tests/test_lint_32bit.py so CI enforces it).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "materialize_tpu"

# Hot-path scope: every device kernel module. repr/ is the sanctioned
# boundary (the aliases + splitmix64 mixing live there) and is NOT scanned.
HOT_PATHS = (
    sorted((PKG / "ops").glob("*.py"))
    + sorted((PKG / "arrangement").glob("*.py"))
    + sorted((PKG / "parallel").glob("exchange*.py"))
    + sorted((PKG / "parallel").glob("netexchange*.py"))
)

# jnp 64-bit dtype mentions in any spelling that creates a device array:
#   jnp.int64 / jnp.uint64 / jnp.float64, jnp.dtype("int64"), astype("uint64")
_FORBIDDEN = re.compile(
    r"""jnp\.(u?int64|float64)\b
      | jnp\.dtype\(\s*['"]((u?int|float)64)['"]\s*\)
      | astype\(\s*['"]((u?int|float)64)['"]\s*\)
    """,
    re.VERBOSE,
)


def lint(paths=HOT_PATHS) -> list[str]:
    violations = []
    for path in paths:
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            code = line.split("#", 1)[0]  # comments may cite the tax freely
            m = _FORBIDDEN.search(code)
            if m:
                try:
                    shown = path.relative_to(REPO)
                except ValueError:
                    shown = path
                violations.append(
                    f"{shown}:{lineno}: forbidden 64-bit "
                    f"device dtype `{m.group(0)}` in a hot-path module — "
                    "import TIME_DTYPE/DIFF_DTYPE/I64_DTYPE from "
                    "materialize_tpu.repr.batch instead"
                )
    return violations


def main() -> int:
    vs = lint()
    for v in vs:
        print(v, file=sys.stderr)
    if vs:
        print(f"lint_32bit: {len(vs)} violation(s)", file=sys.stderr)
        return 1
    print(f"lint_32bit: OK ({len(HOT_PATHS)} hot-path modules clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
