#!/usr/bin/env python
"""Thin shim over `materialize_tpu.analysis` — the dtype-64bit rule.

The scan itself (scope, forbidden spellings, comment handling) lives in
materialize_tpu/analysis/passes/dtype64.py; this wrapper keeps the
historical CLI (`python scripts/lint_32bit.py`) and the `lint(paths)` /
`HOT_PATHS` API that tests/test_lint_32bit.py exercises. Prefer
`python -m materialize_tpu.analysis --rules dtype-64bit` directly.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "materialize_tpu"
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from materialize_tpu.analysis.passes import dtype64  # noqa: E402

# Hot-path scope: every device kernel module. repr/ is the sanctioned
# boundary (the aliases + splitmix64 mixing live there) and is NOT scanned.
HOT_PATHS = (
    sorted((PKG / "ops").glob("*.py"))
    + sorted((PKG / "arrangement").glob("*.py"))
    + sorted((PKG / "parallel").glob("exchange*.py"))
    + sorted((PKG / "parallel").glob("netexchange*.py"))
)


def lint(paths=HOT_PATHS) -> list[str]:
    violations = []
    for path in paths:
        path = Path(path)
        try:
            shown = str(path.relative_to(REPO))
        except ValueError:
            shown = str(path)
        for f in dtype64.scan_lines(shown, path.read_text().splitlines()):
            violations.append(f"{f.path}:{f.line}: {f.message}")
    return violations


def main() -> int:
    vs = lint()
    for v in vs:
        print(v, file=sys.stderr)
    if vs:
        print(f"lint_32bit: {len(vs)} violation(s)", file=sys.stderr)
        return 1
    print(f"lint_32bit: OK ({len(HOT_PATHS)} hot-path modules clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
